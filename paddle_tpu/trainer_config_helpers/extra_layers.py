"""v1 DSL tail: the remaining trainer_config_helpers layer functions
(reference: python/paddle/trainer_config_helpers/layers.py — 133 defs).
Each wrapper adapts v1 semantics (flat sizes, Activation objects, image
[C,H,W] recovery) onto the fluid-style layer library; cite lines refer to
the reference layers.py.

Unsupported-by-design (raise with guidance): cross_entropy_over_beam
(beam-in-training, subsumed by the static-shape scan decoder) and
lambda_cost (listwise LambdaRank needs per-query ragged lists; use
rank_cost pairs instead)."""
from __future__ import annotations

import math

import numpy as np

from .. import layers as L
from ..param_attr import ParamAttr
from .sequence import _Projection, track_layer

__all__ = [
    "bilinear_interp_layer", "block_expand_layer", "clip_layer",
    "conv_shift_layer", "crop_layer", "cross_channel_norm_layer",
    "cross_entropy_with_selfnorm", "ctc_layer", "detection_output_layer",
    "dot_prod_layer", "eos_layer", "factorization_machine",
    "gated_unit_layer", "get_output_layer", "gru_step_naive_layer",
    "hsigmoid", "huber_classification_cost", "huber_regression_cost",
    "img_conv3d_layer", "img_pool3d_layer", "interpolation_layer",
    "kmax_seq_score_layer", "l2_distance_layer", "layer_support",
    "linear_comb_layer", "convex_comb_layer", "LayerType", "LayerOutput",
    "BeamInput", "lstm_step_layer", "maxout_layer",
    "multi_binary_label_cross_entropy", "multibox_loss_layer",
    "multiplex_layer", "nce_layer", "out_prod_layer", "pad_layer",
    "prelu_layer", "printer_layer", "priorbox_layer", "rank_cost",
    "resize_layer", "roi_pool_layer", "rotate_layer", "row_conv_layer",
    "row_l2_norm_layer", "sampling_id_layer", "scale_shift_layer",
    "scale_sub_region_layer", "selective_fc_layer", "seq_concat_layer",
    "seq_slice_layer", "smooth_l1_cost", "spp_layer", "square_error_cost",
    "sub_seq_layer", "sum_cost", "switch_order_layer", "tensor_layer",
    "warp_ctc_layer", "cross_entropy_over_beam", "lambda_cost",
    "context_projection", "dotmul_operator", "conv_operator",
    "sub_nested_seq_layer",
]


def _act_name(a):
    from . import _act_name as f
    return f(a)


def _as_image(input, num_channels=None):
    from . import _as_image as f
    if num_channels is None:
        if input.shape is not None and len(input.shape) == 4:
            return input
        raise ValueError("this layer needs num_channels to recover the "
                         "[C,H,W] image from a flat v1 data layer")
    return f(input, num_channels)


# -- image-shaped layers ----------------------------------------------------
def bilinear_interp_layer(input, out_size_x, out_size_y, num_channels=None,
                          name=None, **kw):
    """layers.py bilinear_interp_layer: resize [C,H,W] bilinearly."""
    img = _as_image(input, num_channels)
    out = L.bilinear_interp(img, out_h=out_size_y, out_w=out_size_x,
                            name=name)
    return track_layer(name, out)


def crop_layer(input, offset, shape=None, axis=2, name=None, **kw):
    """layers.py crop_layer (static offsets form)."""
    full = list(input.shape)
    offs = [0] * len(full)
    for i, o in enumerate(offset):
        offs[axis + i] = o
    if shape is None:
        raise ValueError("crop_layer needs an explicit shape")
    if len(shape) < len(full):
        # ``shape`` covers dims from ``axis`` onward (layers.py crop_layer)
        if axis + len(shape) != len(full):
            raise ValueError(
                f"crop_layer: axis({axis}) + len(shape)({len(shape)}) must "
                f"equal input rank {len(full)}")
        tgt = list(full[:axis]) + list(shape)
    else:
        tgt = list(shape)
    tgt[0] = full[0]
    out = L.crop(input, shape=tgt, offsets=offs, name=name)
    return track_layer(name, out)


def pad_layer(input, pad_c=None, pad_h=None, pad_w=None, name=None, **kw):
    """layers.py pad_layer: zero-pad channel/height/width of [B,C,H,W]."""
    pc, ph, pw = (pad_c or [0, 0]), (pad_h or [0, 0]), (pad_w or [0, 0])
    paddings = [0, 0, pc[0], pc[1], ph[0], ph[1], pw[0], pw[1]]
    out = L.pad(input, paddings=paddings, name=name)
    return track_layer(name, out)


def rotate_layer(input, height, width, num_channels=None, name=None, **kw):
    """layers.py rotate_layer: 90° counter-clockwise rotation of each
    [C,H,W] map (transpose + reverse rows)."""
    img = input
    if input.shape is None or len(input.shape) != 4:
        ch = num_channels or 1
        img = L.reshape(input, [-1, ch, height, width])
    t = L.transpose(img, perm=[0, 1, 3, 2])
    from ..layers.tensor import reverse
    out = reverse(t, axis=2)
    return track_layer(name, out)


def switch_order_layer(input, reshape_axis=None, name=None, **kw):
    """layers.py switch_order_layer: NCHW <-> NHWC."""
    out = L.transpose(input, perm=[0, 2, 3, 1], name=name)
    return track_layer(name, out)


def resize_layer(input, size, name=None, **kw):
    """layers.py resize_layer: reshape rows to the given flat size."""
    out = L.reshape(input, [-1, size], name=name)
    return track_layer(name, out)


def cross_channel_norm_layer(input, name=None, param_attr=None, **kw):
    """layers.py cross_channel_norm_layer (SSD L2Norm): per-pixel L2
    normalization across channels with a learned per-channel scale."""
    normed = L.l2_normalize(input, axis=1)
    sc = scale_shift_layer(normed, per_channel=True, bias=False,
                           param_attr=param_attr)
    return track_layer(name, sc)


def spp_layer(input, num_channels=None, pyramid_height=3, pool_type=None,
              name=None, **kw):
    """layers.py spp_layer (SpatialPyramidPoolLayer.cpp)."""
    img = _as_image(input, num_channels)
    ptype = pool_type.ptype if pool_type is not None else "max"
    out = L.spp(img, pyramid_height=pyramid_height, pool_type=ptype,
                name=name)
    return track_layer(name, out)


def maxout_layer(input, groups, num_channels=None, name=None, **kw):
    img = _as_image(input, num_channels)
    out = L.maxout(img, groups=groups, name=name)
    return track_layer(name, out)


def roi_pool_layer(input, rois, pooled_width, pooled_height,
                   spatial_scale=1.0, num_channels=None, name=None, **kw):
    img = _as_image(input, num_channels)
    out = L.roi_pool(img, rois, pooled_height=pooled_height,
                     pooled_width=pooled_width,
                     spatial_scale=spatial_scale, name=name)
    return track_layer(name, out)


def img_conv3d_layer(input, filter_size, num_filters, num_channels=None,
                     stride=1, padding=0, groups=1, act=None, name=None,
                     param_attr=None, bias_attr=None, **kw):
    """layers.py img_conv3d_layer: NCDHW conv (conv3d_op)."""
    out = L.conv3d(input, num_filters=num_filters, filter_size=filter_size,
                   stride=stride, padding=padding, groups=groups,
                   act=_act_name(act), param_attr=param_attr,
                   bias_attr=bias_attr, name=name)
    return track_layer(name, out)


def img_pool3d_layer(input, pool_size, stride=1, padding=0, pool_type=None,
                     name=None, **kw):
    ptype = pool_type.ptype if pool_type is not None else "max"
    out = L.pool3d(input, pool_size=pool_size, pool_type=ptype,
                   pool_stride=stride, pool_padding=padding, name=name)
    return track_layer(name, out)


def block_expand_layer(input, block_x, block_y, stride_x=1, stride_y=1,
                       padding_x=0, padding_y=0, num_channels=None,
                       name=None, **kw):
    """layers.py block_expand_layer (BlockExpandLayer.cpp = im2sequence)."""
    img = _as_image(input, num_channels)
    out = L.im2sequence(img, filter_size=[block_y, block_x],
                        stride=[stride_y, stride_x],
                        padding=[padding_y, padding_x], name=name)
    return track_layer(name, out)


def prelu_layer(input, name=None, partial_sum=1, channel_shared=None,
                param_attr=None, **kw):
    """layers.py:6676 prelu_layer — partial_sum=1: element-wise alpha;
    = elements-per-channel: channel-wise; = all outputs (or
    channel_shared=True): one shared alpha."""
    n_el = int(np.prod(input.shape[1:])) if input.shape else None
    if channel_shared is True:
        mode = "all"
    elif channel_shared is False:
        mode = "channel"
    elif partial_sum == 1:
        # element-wise alpha needs a static shape; shape-less inputs fall
        # back to the shared-alpha mode (the pre-round-4 behavior)
        mode = "element" if input.shape is not None else "all"
    elif n_el is not None and partial_sum in (None, 0, n_el):
        mode = "all"
    else:
        mode = "channel"
    out = L.prelu(input, mode=mode, param_attr=param_attr, name=name)
    return track_layer(name, out)


# -- elementwise / algebra --------------------------------------------------
def clip_layer(input, min, max, name=None, **kw):  # noqa: A002
    return track_layer(name, L.clip(input, min=float(min), max=float(max),
                                    name=name))


def dot_prod_layer(input1, input2, name=None, **kw):
    """layers.py dot_prod_layer: per-row inner product."""
    out = L.reduce_sum(L.elementwise_mul(input1, input2), dim=-1,
                       keep_dim=True)
    return track_layer(name, out)


def out_prod_layer(input1, input2, name=None, **kw):
    return track_layer(name, L.outer_prod(input1, input2, name=name))


def l2_distance_layer(x, y, name=None, **kw):
    from . import layer_math
    d = L.elementwise_sub(x, y)
    out = L.reduce_sum(L.elementwise_mul(d, d), dim=-1, keep_dim=True)
    return track_layer(name, layer_math.sqrt(out, name=name))


def row_l2_norm_layer(input, name=None, **kw):
    return track_layer(name, L.l2_normalize(input, axis=-1, name=name))


def linear_comb_layer(weights, vectors, size=None, name=None, **kw):
    """layers.py linear_comb_layer: rows of ``vectors`` [B, M*size] grouped
    into M vectors of ``size``, combined with weights [B, M]."""
    size = size or vectors.shape[-1] // weights.shape[-1]
    M = weights.shape[-1]
    v = L.reshape(vectors, [-1, M, size])
    w = L.reshape(weights, [-1, M, 1])
    out = L.reduce_sum(L.elementwise_mul(v, w), dim=1)
    return track_layer(name, out)


# layers.py:5346 — convex_comb_layer is the historical alias
convex_comb_layer = linear_comb_layer


class LayerType:
    """v1 layer-type enumeration (layers.py:155-314).  The values are the
    v1 config-proto type strings — protocol constants, reproduced exactly
    (several are NOT the lowercased member name: POOL_LAYER='pool',
    RANK_COST='rank-cost', CROSS_ENTROPY='multi-class-cross-entropy')."""

    DATA = "data"
    MIXED_LAYER = "mixed"
    LSTMEMORY = "lstmemory"
    GRUMEMORY = "gated_recurrent"
    SEQUENCE_LAST_INSTANCE = "seqlastins"
    SEQUENCE_FIRST_INSTANCE = "seqfirstins"
    SEQUENCE_RESHAPE = "seqreshape"
    POOLING_MAX = "max"
    POOLING_AVG = "average"
    FC_LAYER = "fc"
    COST = "cost"
    COSINE_SIM_VEC = "cos_vm"
    COSINE_SIM = "cos"
    L2_DISTANCE = "l2_distance"
    HSIGMOID = "hsigmoid"
    CONV_LAYER = "conv"
    CONVTRANS_LAYER = "convt"
    EXCONV_LAYER = "exconv"
    EXCONVTRANS_LAYER = "exconvt"
    CUDNNCONV_LAYER = "cudnn_conv"
    CUDNNCONVTRANS_LAYER = "cudnn_convt"
    POOL_LAYER = "pool"
    POOL3D_LAYER = "pool3d"
    BATCH_NORM_LAYER = "batch_norm"
    NORM_LAYER = "norm"
    SUM_TO_ONE_NORM_LAYER = "sum_to_one_norm"
    ROW_L2_NORM_LAYER = "row_l2_norm"
    ADDTO_LAYER = "addto"
    CONCAT_LAYER = "concat"
    CONCAT_PROJ_LAYER = "concat2"
    SEQUENCE_CONCAT_LAYER = "seqconcat"
    LSTM_STEP_LAYER = "lstm_step"
    GRU_STEP_LAYER = "gru_step"
    GET_OUTPUT_LAYER = "get_output"
    EXPAND_LAYER = "expand"
    INTERPOLATION_LAYER = "interpolation"
    BILINEAR_INTERP_LAYER = "bilinear_interp"
    POWER_LAYER = "power"
    SCALING_LAYER = "scaling"
    TRANS_LAYER = "trans"
    ROTATE_LAYER = "rotate"
    DOT_PROD_LAYER = "dot_prod"
    OUT_PROD_LAYER = "out_prod"
    FEATURE_MAP_EXPAND_LAYER = "featmap_expand"
    MEMORY = "memory"
    MAXID_LAYER = "maxid"
    EOSID_LAYER = "eos_id"
    RECURRENT_LAYER = "recurrent"
    CONV_SHIFT_LAYER = "conv_shift"
    TENSOR_LAYER = "tensor"
    SEL_FC_LAYER = "selective_fc"
    SAMPLING_ID_LAYER = "sampling_id"
    SLOPE_INTERCEPT_LAYER = "slope_intercept"
    LINEAR_COMBINATION_LAYER = "convex_comb"
    BLOCK_EXPAND = "blockexpand"
    MAXOUT = "maxout"
    SPP_LAYER = "spp"
    PAD_LAYER = "pad"
    MULTIPLEX_LAYER = "multiplex"
    ROW_CONV_LAYER = "row_conv"
    PRINT_LAYER = "print"
    PRIORBOX_LAYER = "priorbox"
    MULTIBOX_LOSS_LAYER = "multibox_loss"
    DETECTION_OUTPUT_LAYER = "detection_output"
    ROI_POOL_LAYER = "roi_pool"
    CTC_LAYER = "ctc"
    WARP_CTC_LAYER = "warp_ctc"
    CRF_LAYER = "crf"
    CRF_DECODING_LAYER = "crf_decoding"
    NCE_LAYER = "nce"
    CONV3D_LAYER = "conv3d"
    DECONV3D_LAYER = "deconv3d"
    RANK_COST = "rank-cost"
    LAMBDA_COST = "lambda_cost"
    HUBER_REGRESSION = "huber_regression"
    HUBER_CLASSIFICATION = "huber_classification"
    CROSS_ENTROPY = "multi-class-cross-entropy"
    CROSS_ENTROPY_WITH_SELFNORM = "multi_class_cross_entropy_with_selfnorm"
    CROSS_ENTROPY_OVER_BEAM = "cross_entropy_over_beam"
    SOFT_BIN_CLASS_CROSS_ENTROPY = "soft_binary_class_cross_entropy"
    MULTI_BIN_LABEL_CROSS_ENTROPY = "multi_binary_label_cross_entropy"
    SUM_COST = "sum_cost"
    SMOOTH_L1 = "smooth_l1"
    PRELU = "prelu"
    SWITCH_ORDER_LAYER = "switch_order"
    CROP_LAYER = "crop"
    SUB_NESTED_SEQ = "sub_nested_seq"
    CLIP_LAYER = "clip"
    SEQ_SLICE = "seq_slice"
    KMAX_SEQ_SCORE = "kmax_seq_score"
    SCALE_SHIFT_LAYER = "scale_shift"
    RESIZE = "resize"
    SUB_SEQ_LAYER = "subseq"
    SCALE_SUB_REGION_LAYER = "scale_sub_region"
    FACTORIZATION_MACHINE = "factorization_machine"

    @staticmethod
    def is_layer_type(type_name):
        return isinstance(type_name, str)


# The DSL's layer outputs ARE program Variables (layers.py:315 LayerOutput
# tracked name/type/parents; here the Variable carries name/shape/dtype and
# the program records producers) — exporting the class keeps isinstance
# checks in user configs meaningful.
from ..core.program import Variable as LayerOutput  # noqa: E402


class BeamInput:
    """Input triple for cross_entropy_over_beam (layers.py:6355):
    per-candidate scores, selected candidate ids, and the gold index."""

    def __init__(self, candidate_scores, selected_candidates, gold):
        self.candidate_scores = candidate_scores
        self.selected_candidates = selected_candidates
        self.gold = gold


def interpolation_layer(input, weight, name=None, **kw):
    a, b = input
    return track_layer(name, L.interpolation(weight, a, b, name=name))


def conv_shift_layer(a, b, name=None, **kw):
    return track_layer(name, L.conv_shift(a, b, name=name))


def tensor_layer(a, b, size, act=None, name=None, param_attr=None,
                 bias_attr=None, **kw):
    """layers.py tensor_layer = bilinear tensor product."""
    out = L.bilinear_tensor_product(a, b, size, act=_act_name(act),
                                    param_attr=param_attr,
                                    bias_attr=bias_attr, name=name)
    return track_layer(name, out)


def factorization_machine(input, factor_size, name=None, param_attr=None,
                          **kw):
    out = L.factorization_machine(input, factor_size,
                                  param_attr=param_attr, name=name)
    return track_layer(name, out)


def scale_shift_layer(input, name=None, param_attr=None, bias_attr=None,
                      per_channel=False, bias=True, **kw):
    """layers.py scale_shift_layer: y = w * x + b with learned scalar (or
    per-channel, for cross_channel_norm) w and b."""
    from .. import initializer
    from ..layer_helper import LayerHelper
    helper = LayerHelper("scale_shift", param_attr=param_attr,
                         bias_attr=bias_attr, name=name)
    if per_channel:
        c = input.shape[1]
        shape, axis = [c], 1
    else:
        shape, axis = [1], -1
    w = helper.create_parameter(
        param_attr if param_attr is not None else
        ParamAttr(initializer=initializer.Constant(1.0)),
        shape=shape, dtype=input.dtype)
    out = helper.create_variable_for_type_inference(input.dtype, input.shape)
    helper.append_op(type="elementwise_mul",
                     inputs={"X": [input], "Y": [w]},
                     outputs={"Out": [out]}, attrs={"axis": axis})
    if bias:
        b = helper.create_parameter(
            ParamAttr._to_attr(bias_attr) or ParamAttr(),
            shape=shape, dtype=input.dtype, is_bias=True)
        out2 = helper.create_variable_for_type_inference(
            input.dtype, out.shape)
        helper.append_op(type="elementwise_add",
                         inputs={"X": [out], "Y": [b]},
                         outputs={"Out": [out2]}, attrs={"axis": axis})
        out = out2
    return track_layer(name, out)


def scale_sub_region_layer(input, indices, value, name=None, **kw):
    out = L.scale_sub_region(input, indices, value, name=name)
    return track_layer(name, out)


def multiplex_layer(input, name=None, **kw):
    """layers.py multiplex_layer: input[0] is the int selector."""
    return track_layer(name, L.multiplex(list(input[1:]), input[0],
                                         name=name))


def gated_unit_layer(input, size, act=None, name=None, gate_attr=None,
                     gate_param_attr=None, gate_bias_attr=None,
                     inproj_attr=None, inproj_param_attr=None,
                     inproj_bias_attr=None, **kw):
    """layers.py gated_unit_layer: fc(input) * sigmoid(fc_gate(input))."""
    proj = L.fc(input, size=size, act=_act_name(act),
                param_attr=inproj_param_attr, bias_attr=inproj_bias_attr)
    gate = L.fc(input, size=size, act="sigmoid",
                param_attr=gate_param_attr, bias_attr=gate_bias_attr)
    return track_layer(name, L.elementwise_mul(proj, gate, name=name))


def selective_fc_layer(input, size, select=None, act=None, name=None,
                       param_attr=None, bias_attr=None,
                       has_selected_colums=True, **kw):
    """layers.py selective_fc_layer: full fc; with a 0/1 ``select`` matrix
    only the selected output columns survive.  (The reference's sparse
    col-compute is a CPU-cache optimization; under XLA the dense matmul +
    mask is the faster lowering on the MXU.)"""
    out = L.fc(input, size=size, act=_act_name(act), param_attr=param_attr,
               bias_attr=bias_attr)
    if select is not None:
        out = L.elementwise_mul(out, select)
    return track_layer(name, out)


# -- mixed_layer projections / operators ------------------------------------
class context_projection(_Projection):
    """layers.py context_projection: concat of context_len shifted
    timesteps (function/ContextProjectionOp.cpp); width ctx_len*D.  A
    truthy ``padding_attr`` creates trainable boundary rows (the
    reference's trainable_padding) read where the window leaves the
    sequence."""

    def __init__(self, input, context_len, context_start=None,
                 padding_attr=False, **kw):
        super().__init__(input)
        self.context_len = context_len
        self.context_start = context_start
        self.padding_attr = padding_attr

    def _nfd(self):
        return 2

    def build(self, size):
        from ..layer_helper import LayerHelper
        x = self.input
        start = self.context_start if self.context_start is not None \
            else -(self.context_len // 2)
        helper = LayerHelper("sequence_context",
                             param_attr=self.padding_attr or None)
        D = x.shape[-1]
        inputs = {"X": [x]}
        if self.padding_attr:
            begin_pad = max(0, -start)
            end_pad = max(0, start + self.context_len - 1)
            attr = self.padding_attr if isinstance(
                self.padding_attr, ParamAttr) else ParamAttr()
            pad_w = helper.create_parameter(
                attr, shape=[begin_pad + end_pad, D], dtype=x.dtype)
            inputs["PadW"] = [pad_w]
        out = helper.create_variable_for_type_inference(
            x.dtype, tuple(x.shape[:-1]) + (D * self.context_len,),
            lod_level=x.lod_level)
        helper.append_op(type="sequence_context", inputs=inputs,
                         outputs={"Out": [out]},
                         attrs={"contextLength": self.context_len,
                                "contextStart": start})
        return out


class dotmul_operator(_Projection):
    """layers.py dotmul_operator: elementwise a*b of two mixed inputs."""

    def __init__(self, a=None, b=None, scale=1.0, x=None, y=None, **kw):
        self.a, self.b = (a if a is not None else x), \
            (b if b is not None else y)
        super().__init__(self.a)
        self.scale = scale

    def _nfd(self):
        return 2 if getattr(self.a, "lod_level", 0) else 1

    def build(self, size):
        out = L.elementwise_mul(self.a, self.b)
        if self.scale != 1.0:
            out = L.scale(out, scale=self.scale)
        return out


class conv_operator(_Projection):
    """layers.py conv_operator: conv whose filter is another layer's
    output (ConvOperator.cpp)."""

    def __init__(self, img, filter, filter_size, num_filters,  # noqa: A002
                 num_channels=None, stride=1, padding=0,
                 filter_size_y=None, stride_y=None, padding_y=None, **kw):
        super().__init__(img)
        self.img = img
        self.filter = filter
        self.filter_size = filter_size
        self.filter_size_y = filter_size_y or filter_size
        self.num_filters = num_filters
        self.num_channels = num_channels
        self.stride = stride
        self.stride_y = stride_y or stride
        self.padding = padding
        self.padding_y = padding_y if padding_y is not None else padding

    def _nfd(self):
        return 1

    def build(self, size):
        from ..layer_helper import LayerHelper
        img = _as_image(self.img, self.num_channels)
        c = img.shape[1]
        helper = LayerHelper("conv_operator")
        fh, fw = self.filter_size_y, self.filter_size
        oh = (img.shape[2] + 2 * self.padding_y - fh) // self.stride_y + 1
        ow = (img.shape[3] + 2 * self.padding - fw) // self.stride + 1
        out = helper.create_variable_for_type_inference(
            img.dtype, (img.shape[0], self.num_filters, oh, ow))
        helper.append_op(
            type="conv2d_dynamic_filter",
            inputs={"Input": [img], "Filter": [self.filter]},
            outputs={"Output": [out]},
            attrs={"filter_shape": [self.num_filters, c, fh, fw],
                   "strides": [self.stride_y, self.stride],
                   "paddings": [self.padding_y, self.padding]})
        return L.reshape(out, [-1, self.num_filters * oh * ow])


def sub_nested_seq_layer(input, selected_indices, name=None, **kw):
    """layers.py sub_nested_seq_layer: pick subsequences of a level-2
    sequence by per-batch indices."""
    from ..layer_helper import LayerHelper
    helper = LayerHelper("sub_nested_seq", name=name)
    out = helper.create_variable_for_type_inference(
        input.dtype, input.shape, lod_level=max(1, input.lod_level - 1))
    helper.append_op(type="sub_nested_seq",
                     inputs={"X": [input],
                             "Selection": [selected_indices]},
                     outputs={"Out": [out]})
    return track_layer(name, out)


# -- sequence ---------------------------------------------------------------
def seq_concat_layer(a, b, name=None, **kw):
    return track_layer(name, L.sequence_concat([a, b], name=name))


def seq_slice_layer(input, starts, ends=None, sizes=None, name=None, **kw):
    if sizes is None and ends is not None:
        sizes = L.elementwise_sub(ends, starts)
    out = L.sequence_slice(input, starts, sizes, name=name)
    return track_layer(name, out)


def sub_seq_layer(input, offsets, sizes, act=None, bias_attr=None,
                  name=None, **kw):
    """layers.py:7354 sub_seq_layer(input, offsets, sizes) — slice each
    sequence at per-sequence offset/size."""
    return seq_slice_layer(input, starts=offsets, sizes=sizes, name=name)


def kmax_seq_score_layer(input, beam_size=1, name=None, **kw):
    out = L.kmax_sequence_score(input, beam_size=beam_size, name=name)
    return track_layer(name, out)


def row_conv_layer(input, context_len, act=None, name=None,
                   param_attr=None, **kw):
    out = L.row_conv(input, future_context_size=context_len - 1,
                     param_attr=param_attr, act=_act_name(act), name=name)
    return track_layer(name, out)


def eos_layer(input, eos_id, name=None, **kw):
    """layers.py eos_layer: 1.0 where the id equals eos_id."""
    from ..layer_helper import LayerHelper
    helper = LayerHelper("eos", name=name)
    const = L.fill_constant([1], input.dtype, eos_id)
    flag = helper.create_variable_for_type_inference("bool", input.shape)
    helper.append_op(type="equal", inputs={"X": [input], "Y": [const]},
                     outputs={"Out": [flag]})
    return track_layer(name, L.cast(flag, "float32"))


def sampling_id_layer(input, name=None, **kw):
    return track_layer(name, L.sampling_id(input, name=name))


def lstm_step_layer(input, state, size=None, act=None, gate_act=None,
                    state_act=None, name=None, bias_attr=None, **kw):
    """layers.py lstm_step_layer: ONE LSTM step inside a recurrent_group.
    ``input`` is the [B, 4H] pre-projection (mixed_layer output — this
    layer owns no weights, LstmStepLayer.cpp), ``state`` the previous
    cell.  The hidden is the tracked output; the new cell is exposed as
    secondary output 'state' for get_output_layer."""
    size = size or input.shape[-1] // 4
    act_f = getattr(L, _act_name(act) or "tanh")
    gate_f = getattr(L, _act_name(gate_act) or "sigmoid")
    state_f = getattr(L, _act_name(state_act) or "tanh")
    i, f, g, o = L.split(input, 4, dim=-1)
    cell = L.elementwise_add(L.elementwise_mul(gate_f(f), state),
                             L.elementwise_mul(gate_f(i), act_f(g)))
    hidden = L.elementwise_mul(gate_f(o), state_f(cell))
    out = track_layer(name, hidden)
    out.v1_outputs = {"state": cell}
    return out


def gru_step_naive_layer(*args, **kw):
    from .sequence import gru_step_layer
    return gru_step_layer(*args, **kw)


def get_output_layer(input, arg_name, name=None, **kw):
    """layers.py get_output_layer: a named secondary output of a layer
    (e.g. the LSTM cell state)."""
    outs = getattr(input, "v1_outputs", {})
    if arg_name not in outs:
        raise ValueError(
            f"layer {input.name!r} exposes no output {arg_name!r}; "
            f"available: {sorted(outs)} (only step layers with secondary "
            f"outputs support get_output_layer)")
    return track_layer(name, outs[arg_name])


def printer_layer(input, format=None, name=None, **kw):  # noqa: A002
    from .sequence import print_layer
    return print_layer(input=input, name=name)


def layer_support(*attrs):
    """Reference decorator marking supported ExtraAttrs — a no-op here."""
    def deco(f):
        return f
    return deco


# -- costs ------------------------------------------------------------------
def square_error_cost(input, label, name=None, **kw):
    return track_layer(name, L.mean(L.square_error_cost(input, label),
                                    name=name))


def sum_cost(input, name=None, **kw):
    return track_layer(name, L.reduce_sum(input, name=name))


def rank_cost(left, right, label, weight=None, name=None, **kw):
    out = L.mean(L.rank_loss(label, left, right), name=name)
    return track_layer(name, out)


def smooth_l1_cost(input, label, name=None, **kw):
    return track_layer(name, L.mean(L.smooth_l1(input, label), name=name))


def huber_regression_cost(input, label, delta=1.0, name=None, **kw):
    out = L.mean(L.huber_loss(input, label, delta=delta), name=name)
    return track_layer(name, out)


def huber_classification_cost(input, label, name=None, **kw):
    """layers.py huber_classification_cost on ±1 labels."""
    out = L.mean(L.modified_huber_loss(input, label), name=name)
    return track_layer(name, out)


def multi_binary_label_cross_entropy(input, label, name=None, **kw):
    """layers.py multi_binary_label_cross_entropy: sigmoid CE summed over
    the independent binary labels."""
    ce = L.sigmoid_cross_entropy_with_logits(input, label)
    return track_layer(name, L.mean(ce, name=name))


def cross_entropy_with_selfnorm(input, label, softmax_selfnorm_alpha=0.1,
                                name=None, **kw):
    """layers.py cross_entropy_with_selfnorm: CE + alpha * log(Z)^2 where
    input rows are softmax probabilities (Z their sum)."""
    from . import _label_layer
    label = _label_layer(label)
    from . import layer_math
    ce = L.cross_entropy(input, label)
    z = L.reduce_sum(input, dim=-1, keep_dim=True)
    logz = layer_math.log(z)
    pen = L.scale(L.elementwise_mul(logz, logz),
                  scale=softmax_selfnorm_alpha)
    return track_layer(name, L.mean(L.elementwise_add(ce, pen), name=name))


def ctc_layer(input, label, size=None, blank=None, norm_by_times=False,
              name=None, **kw):
    """layers.py ctc_layer (CTCLayer.cpp); the warpctc op is the lowering
    either way (hl_warpctc_wrap subsumed)."""
    blank = blank if blank is not None else (
        (size or input.shape[-1]) - 1)
    out = L.warpctc(input, label, blank=blank,
                    norm_by_times=norm_by_times, name=name)
    return track_layer(name, L.mean(out))


warp_ctc_layer = ctc_layer


def nce_layer(input, label, num_classes=None, num_neg_samples=10,
              param_attr=None, bias_attr=None, name=None, **kw):
    out = L.nce(input, label, num_total_classes=num_classes,
                num_neg_samples=num_neg_samples, param_attr=param_attr,
                bias_attr=bias_attr, name=name)
    return track_layer(name, L.mean(out))


def hsigmoid(input, label, num_classes=None, param_attr=None,
             bias_attr=None, name=None, **kw):
    out = L.hsigmoid(input, label, num_classes=num_classes,
                     param_attr=param_attr, bias_attr=bias_attr, name=name)
    return track_layer(name, L.mean(out))


def lambda_cost(input, score, NDCG_num=5, max_sort_size=-1, name=None,
                **kw):
    """v1 lambda_cost (layers.py:6008; CostLayer.h:252 LambdaCost):
    listwise LambdaRank.  ``input`` is the model's per-document score
    sequence, ``score`` the relevance-label sequence; per-query groups are
    the padded lod_level-1 representation.  The layer value is mean
    NDCG@NDCG_num over the batch's query groups; its backward is the
    lambda gradient (see ops/loss_ops.py), so a training step moves NDCG
    UP — matching the reference layer's semantics, where the printed cost
    is NDCG and rises during training."""
    out = L.lambda_rank(input, score, ndcg_num=NDCG_num,
                        max_sort_size=max_sort_size, name=name)
    return track_layer(name, L.mean(out))


def cross_entropy_over_beam(input, name=None, **kw):
    """Beam-level training cost (layers.py:6377; CrossEntropyOverBeam.h:95).
    ``input``: list of BeamInput(candidate_scores [B,K], selected_candidates
    [B,K] int ids, gold [B] int id), one per beam expansion step.  Returns
    the mean summed cross-entropy of the gold path against each step's beam
    frontier (ops/loss_ops.py for the in-beam/off-beam semantics).  The
    end-to-end demonstration that beam-level training works lives in
    tests/test_generation.py::test_cross_entropy_over_beam_trains."""
    from ..layer_helper import LayerHelper
    if not isinstance(input, (list, tuple)):
        input = [input]
    helper = LayerHelper("cross_entropy_over_beam", name=name)
    scores = [b.candidate_scores for b in input]
    out = helper.create_variable_for_type_inference(
        "float32", (scores[0].shape[0], 1))
    helper.append_op(
        type="cross_entropy_over_beam",
        inputs={"Scores": scores,
                "Cands": [b.selected_candidates for b in input],
                "Gold": [b.gold for b in input]},
        outputs={"Out": [out]})
    return track_layer(name, L.mean(out))


# -- detection --------------------------------------------------------------
def priorbox_layer(input, image, min_size, max_size=(), aspect_ratio=(),
                   variance=(0.1, 0.1, 0.2, 0.2), name=None, **kw):
    """layers.py priorbox_layer -> fluid prior_box (detection.py)."""
    boxes, variances = L.detection.prior_box(
        input, image, min_sizes=list(min_size),
        max_sizes=list(max_size) or None,
        aspect_ratios=list(aspect_ratio) or [1.0],
        variance=list(variance), name=name)
    out = track_layer(name, boxes)
    out.v1_outputs = {"variances": variances}
    return out


def multibox_loss_layer(input_loc, input_conf, priorbox, label, gt_box,
                        num_classes, overlap_threshold=0.5,
                        neg_pos_ratio=3.0, name=None, **kw):
    """layers.py multibox_loss_layer -> fluid ssd_loss."""
    variances = getattr(priorbox, "v1_outputs", {}).get("variances")
    out = L.detection.ssd_loss(
        input_loc, input_conf, gt_box, label, priorbox, variances,
        overlap_threshold=overlap_threshold,
        neg_pos_ratio=neg_pos_ratio, background_label=0)
    return track_layer(name, L.mean(out, name=name))


def detection_output_layer(input_loc, input_conf, priorbox, num_classes,
                           nms_threshold=0.45, nms_top_k=400, keep_top_k=200,
                           confidence_threshold=0.01, background_id=0,
                           name=None, **kw):
    """layers.py detection_output_layer: decode loc offsets against the
    priors (box_coder) then class-wise NMS (detection_output)."""
    variances = getattr(priorbox, "v1_outputs", {}).get("variances")
    decoded = L.detection.box_coder(priorbox, variances, input_loc)
    out = L.detection.detection_output(
        input_conf, decoded,
        nms_threshold=nms_threshold, nms_top_k=nms_top_k,
        keep_top_k=keep_top_k, score_threshold=confidence_threshold,
        background_label=background_id, name=name)
    return track_layer(name, out)
