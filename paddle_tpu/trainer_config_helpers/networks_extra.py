"""v1 networks.py tail: the remaining composite network helpers
(reference: python/paddle/trainer_config_helpers/networks.py — cite lines
per function).  Composites only — each builds on the DSL layer wrappers,
exactly as the reference composes them."""
from __future__ import annotations

import math

from .. import layers as L
from .sequence import track_layer

__all__ = [
    "simple_img_conv_pool", "img_conv_bn_pool", "img_separable_conv",
    "small_vgg", "vgg_16_network", "lstmemory_unit", "gru_unit",
    "simple_gru2", "bidirectional_gru", "dot_product_attention",
    "multi_head_attention",
]


def simple_img_conv_pool(input, filter_size, num_filters, pool_size,
                         name=None, pool_type=None, act=None, groups=1,
                         conv_stride=1, conv_padding=0, bias_attr=None,
                         num_channel=None, num_channels=None,
                         param_attr=None, shared_bias=True,
                         conv_layer_attr=None, pool_stride=1,
                         pool_padding=0, pool_layer_attr=None):
    """networks.py:144 — conv then pool."""
    from . import img_conv_layer, img_pool_layer
    conv = img_conv_layer(
        input=input, filter_size=filter_size, num_filters=num_filters,
        num_channels=num_channel or num_channels, act=act, groups=groups,
        stride=conv_stride, padding=conv_padding, bias_attr=bias_attr,
        param_attr=param_attr, layer_attr=conv_layer_attr)
    out = img_pool_layer(
        input=conv, pool_size=pool_size, pool_type=pool_type,
        stride=pool_stride, padding=pool_padding,
        layer_attr=pool_layer_attr)
    return track_layer(name, out)


def img_conv_bn_pool(input, filter_size, num_filters, pool_size, name=None,
                     pool_type=None, act=None, groups=1, conv_stride=1,
                     conv_padding=0, conv_bias_attr=None,
                     num_channel=None, num_channels=None,
                     conv_param_attr=None, shared_bias=True,
                     conv_layer_attr=None, bn_param_attr=None,
                     bn_bias_attr=None, bn_layer_attr=None, pool_stride=1,
                     pool_padding=0, pool_layer_attr=None):
    """networks.py:231 — conv, batch-norm (activation on the BN), pool."""
    from . import batch_norm_layer, img_conv_layer, img_pool_layer
    conv = img_conv_layer(
        input=input, filter_size=filter_size, num_filters=num_filters,
        num_channels=num_channel or num_channels, act=None, groups=groups,
        stride=conv_stride, padding=conv_padding, bias_attr=conv_bias_attr,
        param_attr=conv_param_attr, layer_attr=conv_layer_attr)
    bn = batch_norm_layer(input=conv, act=act, bias_attr=bn_bias_attr,
                          param_attr=bn_param_attr,
                          layer_attr=bn_layer_attr)
    out = img_pool_layer(
        input=bn, pool_size=pool_size, pool_type=pool_type,
        stride=pool_stride, padding=pool_padding,
        layer_attr=pool_layer_attr)
    return track_layer(name, out)


def img_separable_conv(input, num_channels, num_out_channels, filter_size,
                       stride=1, padding=0, depth_multiplier=1, act=None,
                       bias_attr=None, param_attr=None, shared_bias=True,
                       layer_attr=None, name=None):
    """networks.py:439 — depthwise conv (groups == channels) followed by a
    1x1 pointwise conv."""
    from . import img_conv_layer
    depthwise = img_conv_layer(
        input=input, filter_size=filter_size,
        num_filters=num_channels * depth_multiplier,
        num_channels=num_channels, groups=num_channels,
        stride=stride, padding=padding, act=None, bias_attr=bias_attr,
        param_attr=param_attr, layer_attr=layer_attr)
    pointwise = img_conv_layer(
        input=depthwise, filter_size=1, num_filters=num_out_channels,
        num_channels=num_channels * depth_multiplier, stride=1, padding=0,
        act=act, bias_attr=bias_attr, param_attr=param_attr,
        layer_attr=layer_attr)
    return track_layer(name, pointwise)


def small_vgg(input_image, num_channels, num_classes):
    """networks.py:517 — the CIFAR vgg (4 conv groups then fc+bn+fc)."""
    from . import (MaxPooling, ReluActivation, SoftmaxActivation,
                   batch_norm_layer, dropout_layer, fc_layer,
                   img_conv_group, img_pool_layer)

    def vgg_block(ipt, num_filter, times, dropouts, channels=None):
        return img_conv_group(
            input=ipt, num_channels=channels, pool_size=2, pool_stride=2,
            conv_num_filter=[num_filter] * times, conv_filter_size=3,
            conv_act=ReluActivation(), conv_with_batchnorm=True,
            conv_batchnorm_drop_rate=dropouts, pool_type=MaxPooling())

    tmp = vgg_block(input_image, 64, 2, [0.3, 0], num_channels)
    tmp = vgg_block(tmp, 128, 2, [0.4, 0])
    tmp = vgg_block(tmp, 256, 3, [0.4, 0.4, 0])
    tmp = vgg_block(tmp, 512, 3, [0.4, 0.4, 0])
    tmp = img_pool_layer(input=tmp, stride=2, pool_size=2,
                         pool_type=MaxPooling())
    tmp = dropout_layer(input=tmp, dropout_rate=0.5)
    tmp = fc_layer(input=tmp, size=512, act=None)
    tmp = batch_norm_layer(input=tmp, act=ReluActivation())
    return fc_layer(input=tmp, size=num_classes, act=SoftmaxActivation())


def vgg_16_network(input_image, num_channels, num_classes=1000):
    """networks.py:547 — the canonical VGG-16."""
    from . import (MaxPooling, ReluActivation, SoftmaxActivation,
                   dropout_layer, fc_layer, img_conv_group)

    def block(ipt, filters, times, channels=None):
        return img_conv_group(
            input=ipt, num_channels=channels, pool_size=2, pool_stride=2,
            conv_num_filter=[filters] * times, conv_filter_size=3,
            conv_act=ReluActivation(), pool_type=MaxPooling())

    tmp = block(input_image, 64, 2, num_channels)
    tmp = block(tmp, 128, 2)
    tmp = block(tmp, 256, 3)
    tmp = block(tmp, 512, 3)
    tmp = block(tmp, 512, 3)
    tmp = fc_layer(input=tmp, size=4096, act=ReluActivation())
    tmp = dropout_layer(input=tmp, dropout_rate=0.5)
    tmp = fc_layer(input=tmp, size=4096, act=ReluActivation())
    tmp = dropout_layer(input=tmp, dropout_rate=0.5)
    return fc_layer(input=tmp, size=num_classes, act=SoftmaxActivation())


def lstmemory_unit(input, out_memory=None, name=None, size=None,
                   param_attr=None, act=None, gate_act=None, state_act=None,
                   input_proj_bias_attr=None, input_proj_layer_attr=None,
                   lstm_bias_attr=None, lstm_layer_attr=None, **kw):
    """networks.py:717 — one projected LSTM step for a recurrent_group
    body: mixed full-matrix projection to 4H, then lstm_step_layer against
    the memory of this unit's own output and cell."""
    from . import _act_name
    from .extra_layers import get_output_layer, lstm_step_layer
    from .sequence import memory
    size = size or input.shape[-1] // 4
    out_mem = out_memory if out_memory is not None else \
        memory(name=name, size=size)
    state_mem = memory(name="%s@state" % name, size=size)
    proj = L.fc([input, out_mem], size=size * 4, num_flatten_dims=1,
                param_attr=param_attr, bias_attr=input_proj_bias_attr)
    hidden = lstm_step_layer(proj, state_mem, size=size, act=act,
                             gate_act=gate_act, state_act=state_act,
                             name=name)
    track_layer("%s@state" % name, get_output_layer(hidden, "state"))
    return hidden


def gru_unit(input, memory_boot=None, name=None, size=None,
             param_attr=None, act=None, gate_act=None,
             gru_bias_attr=None, gru_layer_attr=None, naive=False, **kw):
    """networks.py:940 — one GRU step for a recurrent_group body."""
    from .sequence import gru_step_layer, memory
    size = size or input.shape[-1] // 3
    out_mem = memory(name=name, size=size, boot_layer=memory_boot)
    return gru_step_layer(input, out_mem, size=size, act=act,
                          gate_act=gate_act, param_attr=param_attr,
                          bias_attr=gru_bias_attr, name=name)


def simple_gru2(input, size, name=None, reverse=False, mixed_param_attr=None,
                mixed_bias_attr=None, gru_param_attr=None,
                gru_bias_attr=None, act=None, gate_act=None,
                mixed_layer_attr=None, gru_cell_attr=None, **kw):
    """networks.py:1163 — same math as simple_gru, grouped like the v1
    fast implementation (one projection + grumemory)."""
    from .sequence import simple_gru
    return simple_gru(input=input, size=size, name=name, reverse=reverse,
                      act=act, gate_act=gate_act,
                      param_attr=gru_param_attr or mixed_param_attr,
                      bias_attr=gru_bias_attr or mixed_bias_attr)


def bidirectional_gru(input, size, name=None, return_seq=False,
                      fwd_act=None, fwd_gate_act=None, bwd_act=None,
                      bwd_gate_act=None, **kw):
    """networks.py:1226 — forward + backward GRU; concat of the two last
    steps (or the full sequences with return_seq=True)."""
    from . import _act_name
    fwd_proj = L.fc(input, size=size * 3, num_flatten_dims=2)
    fwd = L.dynamic_gru(fwd_proj, size=size,
                        candidate_activation=_act_name(fwd_act) or "tanh",
                        gate_activation=_act_name(fwd_gate_act) or "sigmoid")
    bwd_proj = L.fc(input, size=size * 3, num_flatten_dims=2)
    bwd = L.dynamic_gru(bwd_proj, size=size, is_reverse=True,
                        candidate_activation=_act_name(bwd_act) or "tanh",
                        gate_activation=_act_name(bwd_gate_act) or "sigmoid")
    if return_seq:
        out = L.concat([fwd, bwd], axis=-1)
    else:
        out = L.concat([L.sequence_last_step(fwd),
                        L.sequence_first_step(bwd)], axis=-1)
    return track_layer(name, out)


def dot_product_attention(encoded_sequence, attended_sequence,
                          transformed_state, softmax_param_attr=None,
                          name=None, **kw):
    """networks.py:1498 — dot-product attention: score each encoder
    position by <transformed_state, encoded_t>, softmax over the sequence,
    weight the attended sequence."""
    expanded = L.sequence_expand(transformed_state, encoded_sequence)
    scores = L.reduce_sum(L.elementwise_mul(expanded, encoded_sequence),
                          dim=-1, keep_dim=True)
    weight = L.sequence_softmax(scores)
    scaled = L.elementwise_mul(attended_sequence, weight)
    return track_layer(name, L.sequence_pool(scaled, "sum"))


def multi_head_attention(query, key, value, key_proj_size, value_proj_size,
                         head_num, attention_type="dot-product attention",
                         softmax_param_attr=None, name=None, **kw):
    """networks.py:1580 — project q/k/v per head, scaled-dot attention
    over the key sequence per head, concat head contexts.  TPU note: the
    per-head loop builds one fused graph; for long sequences prefer
    layers.flash_attention."""
    heads = []
    for h in range(head_num):
        q = L.fc(query, size=key_proj_size // head_num, bias_attr=False)
        k = L.fc(key, size=key_proj_size // head_num, num_flatten_dims=2,
                 bias_attr=False)
        v = L.fc(value, size=value_proj_size // head_num,
                 num_flatten_dims=2, bias_attr=False)
        qe = L.sequence_expand(q, k)
        scores = L.scale(
            L.reduce_sum(L.elementwise_mul(qe, k), dim=-1, keep_dim=True),
            scale=1.0 / math.sqrt(key_proj_size // head_num))
        weight = L.sequence_softmax(scores)
        heads.append(L.sequence_pool(L.elementwise_mul(v, weight), "sum"))
    out = L.concat(heads, axis=-1) if len(heads) > 1 else heads[0]
    return track_layer(name, out)
