"""v1 sequence/generation DSL: recurrent_group, memory(), mixed_layer +
projections, recurrent/lstm/gru groups, CRF layers, beam-search generation.

Reference surface: python/paddle/trainer_config_helpers/layers.py —
memory():4079, recurrent_group():3492, mixed_layer():817 + the projection
family (full_matrix_projection():548, table_projection():588,
identity_projection():682, trans_full_matrix_projection():633,
dotmul_projection():722, scaling_projection():651), recurrent_layer():3225,
lstmemory_group (networks.py:771), beam_search():3905 with
StaticInput/GeneratedInput, crf_layer():5791, crf_decoding_layer():5852.

TPU-native lowering: a recurrent_group becomes a ``StaticRNN`` sub-block
that the executor runs as ONE lax.scan (padded batch + @LEN masking — no
per-sequence dispatch like the reference's RecurrentGradientMachine,
gserver/gradientmachines/RecurrentGradientMachine.cpp).  ``memory()`` maps
to scan carries, resolved to their update layer by v1's name-matching
convention at group close.  Generation maps onto the static-shape
``BeamSearchDecoder`` scan (layers/generation.py) — beams ride the batch
dimension, statics are tiled per beam by the lowering.
"""
from __future__ import annotations

import numpy as np

from .. import layers as L
from ..core import unique_name
from ..layer_helper import LayerHelper
from ..param_attr import ParamAttr

__all__ = [
    "memory", "recurrent_group", "StaticInput", "GeneratedInput",
    "SubsequenceInput", "mixed_layer", "MixedLayerType",
    "full_matrix_projection", "trans_full_matrix_projection",
    "table_projection", "identity_projection", "dotmul_projection",
    "scaling_projection", "recurrent_layer", "lstmemory_group",
    "grumemory", "gru_group", "simple_gru", "beam_search",
    "crf_layer", "crf_decoding_layer",
    "sum_evaluator", "chunk_evaluator", "seqtext_printer_evaluator",
    "classification_error_evaluator",
    "slice_projection",
    "maxid_layer", "pooling_layer", "sequence_conv_pool",
    "bidirectional_lstm", "expand_layer", "scaling_layer",
    "simple_attention", "gru_step_layer",
    "power_layer", "slope_intercept_layer", "sum_to_one_norm_layer",
    "cos_sim", "trans_layer", "repeat_layer", "seq_reshape_layer",
    "print_layer",
]


# ---------------------------------------------------------------------------
# group context: memory()/layer-name resolution inside a step function
# ---------------------------------------------------------------------------
class _GroupCtx:
    """Per-recurrent_group bookkeeping.  v1 links a memory to its updater by
    layer NAME (memory(name="s") <-> fc_layer(name="s")); layer wrappers call
    ``track`` so the group can resolve the pairs when the step closes."""

    def __init__(self, rnn, kind):
        self.rnn = rnn
        self.kind = kind            # "rnn" | "beam"
        self.layer_by_name = {}
        self.pending = []           # (mem var, layer name)
        self.boot_by_name = {}


_group_stack: list = []


def track_layer(name, out):
    """Record a named layer output for memory resolution (and config-level
    Outputs())."""
    from . import _state
    if name:
        if _group_stack:
            _group_stack[-1].layer_by_name[name] = out
        _state.named_layers[name] = out
    return out


def memory(name=None, size=None, boot_layer=None, is_seq=False,
           boot_with_const_id=None, boot_bias=None, **kw):
    """v1 memory (layers.py:4079): the previous step's output of the layer
    called ``name``; zeros (or ``boot_layer``) at t=0."""
    if not _group_stack:
        raise RuntimeError("memory() must be called inside a "
                           "recurrent_group/beam_search step function")
    g = _group_stack[-1]
    if g.kind == "beam":
        if boot_layer is None:
            raise ValueError("beam_search memory needs boot_layer (the "
                             "per-sequence decoder init)")
        mem = g.rnn.memory(init=boot_layer)
    elif boot_layer is not None:
        mem = g.rnn.memory(init=boot_layer)
    else:
        mem = g.rnn.memory(shape=[size])
    g.pending.append((mem, name))
    return mem


def _resolve_memories(g):
    for mem, nm in g.pending:
        upd = g.layer_by_name.get(nm)
        if upd is None:
            raise ValueError(
                f"memory(name={nm!r}) has no matching layer named {nm!r} "
                f"inside the step function (v1 name-link convention)")
        g.rnn.update_memory(mem, upd)


class StaticInput:
    """Read-only non-sequence input to a recurrent_group/beam_search step
    (layers.py StaticInput): the same tensor every step."""

    def __init__(self, input, size=None, is_seq=False):
        self.input = input
        self.size = size
        self.is_seq = is_seq


class SubsequenceInput:
    """Nested-sequence input marker (layers.py SubsequenceInput): the outer
    recurrent_group iterates SUBSEQUENCES — each step receives one padded
    inner sequence [B, T', ...] with its own lengths.  Declaring it here
    promotes the wrapped var to lod_level 2 ([B, S, T', ...] + @LEN/@LEN2
    companions), mirroring v1 where the data provider declared nesting."""

    def __init__(self, input):
        self.input = input


class GeneratedInput:
    """Generation-mode input: the embedding of the previously generated
    token (layers.py GeneratedInput)."""

    def __init__(self, size, embedding_name, embedding_size):
        self.size = size
        self.embedding_name = embedding_name
        self.embedding_size = embedding_size


def recurrent_group(step, input, name=None, reverse=False, **kw):
    """v1 recurrent_group (layers.py:3492) -> StaticRNN scan.

    ``input``: sequence var(s) ([B,T,D] padded + @LEN) and/or StaticInput.
    The step function receives per-step [B,D] slices (statics unchanged) and
    returns the step output(s); memories declared inside link by name.
    """
    items = list(input) if isinstance(input, (list, tuple)) else [input]
    if reverse and any(isinstance(it, SubsequenceInput) for it in items):
        raise NotImplementedError(
            "recurrent_group(reverse=True) over SubsequenceInput is not "
            "supported: reversing nested sequences needs both subsequence "
            "and token order flipped; no shipped reference config uses it")
    for it in items:
        if isinstance(it, SubsequenceInput):
            # declare nesting on the underlying var: runtime arrays are
            # [B, S, T', ...] with @LEN ([B] subseq counts) and @LEN2
            # ([B, S] token counts) companions
            v = it.input
            if v.lod_level < 2:
                v.lod_level = 2
                if v.shape is not None:
                    v.shape = (v.shape[0], -1) + tuple(v.shape[1:])
                # an embedding is per-token, so nesting originates at its id
                # DATA layer — promote it too so the DataFeeder pads nested
                # rows (the provider-declares-nesting role in v1).  The
                # var's last writer is the @LEN copy op, whose X is the ids.
                op = getattr(v, "op", None)
                src_name = None
                if op is not None and op.type == "lookup_table":
                    src_name = op.inputs["Ids"][0]
                elif op is not None and op.type == "copy_len":
                    src_name = op.inputs["X"][0]
                blk = v.block
                if src_name and src_name in blk.vars:
                    ids = blk.vars[src_name]
                    if getattr(ids, "is_data", False) and ids.lod_level < 2:
                        ids.lod_level = 2
                        if ids.shape is not None:
                            ids.shape = (ids.shape[0], -1) + \
                                tuple(ids.shape[1:])
    if reverse:
        items = [it if isinstance(it, (StaticInput, SubsequenceInput))
                 else L.sequence_reverse(it) for it in items]
    rnn = L.StaticRNN(name=name)
    g = _GroupCtx(rnn, "rnn")
    with rnn.step():
        _group_stack.append(g)
        try:
            args = []
            # sequence inputs must register first so memory() can size its
            # zero-init from the sequence's batch dim
            for it in items:
                if isinstance(it, SubsequenceInput):
                    ipt = rnn.step_input(it.input)
                    ipt.lod_level = 1     # each step is itself a sequence
                    args.append(ipt)
                elif not isinstance(it, StaticInput):
                    ipt = rnn.step_input(it)
                    if hasattr(it, "v1_size"):
                        ipt.v1_size = it.v1_size   # id inputs keep their
                        #                            vocab for embeddings
                    args.append(ipt)
                else:
                    args.append(None)
            for i, it in enumerate(items):
                if isinstance(it, StaticInput):
                    args[i] = it.input
            outs = step(*args)
            outs = list(outs) if isinstance(outs, (list, tuple)) else [outs]
            for o in outs:
                rnn.step_output(o)
            _resolve_memories(g)
        finally:
            _group_stack.pop()
    res = rnn.outputs
    if any(isinstance(it, SubsequenceInput) for it in items):
        for r in res:
            # stacked per-subsequence outputs are sequences of sequences
            r.lod_level = 2
    if reverse:
        res = [L.sequence_reverse(r) for r in res]
    return res[0] if len(res) == 1 else res


# ---------------------------------------------------------------------------
# projections + mixed_layer
# ---------------------------------------------------------------------------
class _Projection:
    def __init__(self, input, param_attr=None):
        self.input = input
        self.param_attr = param_attr

    def _nfd(self):
        v = self.input
        return 2 if getattr(v, "lod_level", 0) else 1


class full_matrix_projection(_Projection):
    """y = x * W  (layers.py:548)."""

    def build(self, size):
        return L.fc(self.input, size=size, num_flatten_dims=self._nfd(),
                    param_attr=self.param_attr, bias_attr=False)


class trans_full_matrix_projection(_Projection):
    """y = x * W^T, W declared [size, in] (layers.py:633) — the weight-tying
    projection (shares e.g. an embedding table by param name)."""

    def build(self, size):
        x = self.input
        in_dim = x.shape[-1]
        helper = LayerHelper("trans_fc", param_attr=self.param_attr)
        w = helper.create_parameter(self.param_attr, shape=[size, in_dim],
                                    dtype=x.dtype)
        return L.matmul(x, w, transpose_y=True)


class table_projection(_Projection):
    """Embedding-table lookup of integer ids (layers.py:588)."""

    def build(self, size):
        ids = self.input
        vocab = getattr(ids, "v1_size", None)
        if vocab is None:
            raise ValueError("table_projection input must be an id "
                             "data_layer (its size is the vocab)")
        if ids.dtype != np.dtype("int64"):
            ids.dtype = np.dtype("int64")
            ids.lod_level = 1
            ids.shape = (-1, -1)
        return L.embedding(ids, size=[vocab, size],
                           param_attr=self.param_attr)


class identity_projection(_Projection):
    def __init__(self, input, offset=None, size=None):
        super().__init__(input)
        self.offset = offset
        self.size = size

    def build(self, size):
        if self.offset is None:
            return self.input
        return L.slice(self.input, axes=[len(self.input.shape) - 1],
                       starts=[self.offset], ends=[self.offset + size])


class slice_projection(_Projection):
    """Concat of index ranges from the input (SliceProjection.cpp): for a
    conv output the slices select CHANNEL ranges, else feature ranges."""

    def __init__(self, input, slices):
        super().__init__(input)
        self.slices = list(slices)

    def build(self, size=0):
        x = self.input
        axis = 1 if (x.shape is not None and len(x.shape) == 4) else \
            (len(x.shape) - 1 if x.shape else -1)
        parts = [L.slice(x, axes=[axis], starts=[s], ends=[e])
                 for s, e in self.slices]
        return parts[0] if len(parts) == 1 else L.concat(parts, axis=axis)


class dotmul_projection(_Projection):
    """y = x . w (per-feature scale, layers.py:722)."""

    def build(self, size):
        x = self.input
        if not size:
            size = x.shape[-1]      # projection-inferred mixed/concat
        helper = LayerHelper("dotmul_proj", param_attr=self.param_attr)
        w = helper.create_parameter(self.param_attr, shape=[size],
                                    dtype=x.dtype)
        return L.elementwise_mul(x, w, axis=-1)


class scaling_projection(_Projection):
    """y = w * x with scalar w (layers.py:651)."""

    def build(self, size):
        x = self.input
        helper = LayerHelper("scaling_proj", param_attr=self.param_attr)
        w = helper.create_parameter(self.param_attr, shape=[1],
                                    dtype=x.dtype)
        return L.elementwise_mul(x, w)


class MixedLayerType:
    """mixed_layer handle: usable as ``mixed_layer(input=[proj, ...])`` or
    as the v1 context-manager form::

        with mixed_layer(size=H) as m:
            m += full_matrix_projection(input=x)

    On close the object BECOMES the output Variable (class swap), so it can
    be passed to any later layer untouched — the v1 configs do exactly
    that."""

    def __init__(self, size, act=None, bias_attr=None, name=None,
                 layer_attr=None):
        self.size = size
        self.act = act
        self.bias_attr = bias_attr
        self.name = name
        self.layer_attr = layer_attr
        self.projections = []

    def __iadd__(self, proj):
        if not isinstance(proj, _Projection):
            proj = identity_projection(proj)
        self.projections.append(proj)
        return self

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        if exc_type is None:
            self._finalize()
        return False

    def _finalize(self):
        from . import _act_name, _apply_layer_attr
        if not self.projections:
            raise ValueError("mixed_layer closed with no projections")
        parts = [p.build(self.size) for p in self.projections]
        out = parts[0]
        for p in parts[1:]:
            out = L.elementwise_add(out, p)
        if self.bias_attr not in (None, False):
            helper = LayerHelper("mixed_bias")
            battr = self.bias_attr if isinstance(self.bias_attr, ParamAttr) \
                else ParamAttr()
            # size=0 (projection-inferred mixed, e.g. conv projections):
            # bias per channel for 4-D outputs, per feature otherwise
            if out.shape is not None and len(out.shape) == 4:
                bsize, axis = out.shape[1], 1
            else:
                bsize = self.size or (out.shape[-1] if out.shape else 1)
                axis = -1
            b = helper.create_parameter(battr, shape=[bsize],
                                        dtype=out.dtype, is_bias=True)
            out = L.elementwise_add(out, b, axis=axis)
        a = _act_name(self.act)
        if a:
            out = getattr(L, a)(out)
        out = _apply_layer_attr(out, self.layer_attr)
        track_layer(self.name, out)
        # become the Variable: v1 passes the mixed_layer object itself to
        # downstream layers (class swap shares the var's state dict)
        self.__class__ = out.__class__
        self.__dict__ = out.__dict__
        return self


def mixed_layer(size=0, input=None, act=None, bias_attr=None, name=None,
                layer_attr=None, **kw):
    m = MixedLayerType(size, act=act, bias_attr=bias_attr, name=name,
                       layer_attr=layer_attr)
    if input is None:
        return m               # context-manager form
    projs = input if isinstance(input, (list, tuple)) else [input]
    for p in projs:
        m += p
    return m._finalize()


# ---------------------------------------------------------------------------
# recurrent layers built on the group machinery
# ---------------------------------------------------------------------------
def recurrent_layer(input, act=None, bias_attr=None, param_attr=None,
                    name=None, reverse=False, **kw):
    """v1 simple full-matrix recurrence (layers.py:3225, RecurrentLayer.cpp):
    out_t = act(in_t + out_{t-1} * W + b); in is the pre-projected input."""
    from . import _act_name
    size = input.shape[-1]
    nm = name or unique_name.generate("recurrent")

    def _step(x):
        mem = memory(name=nm, size=size)
        proj = L.fc(mem, size=size, num_flatten_dims=1,
                    param_attr=param_attr, bias_attr=bias_attr)
        out = L.elementwise_add(x, proj)
        a = _act_name(act)
        if a:
            out = getattr(L, a)(out)
        return track_layer(nm, out)

    return recurrent_group(step=_step, input=input, reverse=reverse)


def lstmemory_group(input, size=None, name=None, reverse=False, act=None,
                    gate_act=None, state_act=None, param_attr=None,
                    lstm_bias_attr=None, **kw):
    """networks.py:771 lstmemory_group.  The per-step LSTM unit over the
    pre-projected [B,T,4H] input is exactly the fused ``lstm`` scan op —
    same math, one kernel (no per-step Python group needed)."""
    from . import _act_name
    size = size or input.shape[-1] // 4
    hid, _ = L.dynamic_lstm(
        input, size=size * 4, is_reverse=reverse, param_attr=param_attr,
        bias_attr=lstm_bias_attr, use_peepholes=True,
        gate_activation=_act_name(gate_act) or "sigmoid",
        cell_activation=_act_name(state_act) or "tanh",
        candidate_activation=_act_name(act) or "tanh", name=name)
    return track_layer(name, hid)


def grumemory(input, size=None, name=None, reverse=False, act=None,
              gate_act=None, param_attr=None, bias_attr=None, **kw):
    """v1 grumemory (layers.py:3056): input is the [B,T,3H] projection."""
    from . import _act_name
    size = size or input.shape[-1] // 3
    hid = L.dynamic_gru(
        input, size=size, is_reverse=reverse, param_attr=param_attr,
        bias_attr=bias_attr,
        gate_activation=_act_name(gate_act) or "sigmoid",
        candidate_activation=_act_name(act) or "tanh", name=name)
    return track_layer(name, hid)


gru_group = grumemory


def simple_gru(input, size, name=None, reverse=False, act=None,
               gate_act=None, mixed_param_attr=None, gru_param_attr=None,
               mixed_bias_param_attr=None, gru_bias_attr=None, **kw):
    """networks.py simple_gru: fc(3H) + grumemory."""
    proj = L.fc(input, size=size * 3, num_flatten_dims=2,
                param_attr=mixed_param_attr,
                bias_attr=mixed_bias_param_attr)
    return grumemory(proj, size=size, name=name, reverse=reverse, act=act,
                     gate_act=gate_act, param_attr=gru_param_attr,
                     bias_attr=gru_bias_attr)


# ---------------------------------------------------------------------------
# CRF
# ---------------------------------------------------------------------------
def _seq_label_layer(label):
    """Coerce a v1 label data_layer into a per-token id sequence [B,T]."""
    if getattr(label, "is_data", False) and \
            label.dtype != np.dtype("int64"):
        label.dtype = np.dtype("int64")
        label.lod_level = 1
        label.shape = (-1, -1)
    return label


def crf_layer(input, label, size=None, param_attr=None, name=None,
              weight=None, layer_attr=None, **kw):
    """v1 CRFLayer (layers.py:5791): negative log-likelihood cost."""
    label = _seq_label_layer(label)
    ll = L.linear_chain_crf(input, label, param_attr=param_attr, name=name)
    cost = L.mean(ll)
    return track_layer(name, cost)


def crf_decoding_layer(input, size=None, label=None, param_attr=None,
                       name=None, layer_attr=None, **kw):
    """v1 CRFDecodingLayer: viterbi path (with label: per-token error)."""
    if label is not None:
        label = _seq_label_layer(label)
    out = L.crf_decoding(input, param_attr, label=label, name=name)
    return track_layer(name, out)


# ---------------------------------------------------------------------------
# generation: v1 beam_search -> BeamSearchDecoder scan
# ---------------------------------------------------------------------------
def beam_search(step, input, bos_id, eos_id, beam_size=1, max_length=30,
                name=None, num_results_per_sample=None, **kw):
    """v1 beam_search (layers.py:3905).  ``input`` mixes StaticInput items
    and exactly one GeneratedInput; the step function returns the next-token
    probability layer [*, V].  Returns the generated ids [B, K, max_len]
    (registered as ``__beam_search_predict__`` for Outputs())."""
    items = list(input) if isinstance(input, (list, tuple)) else [input]
    gens = [it for it in items if isinstance(it, GeneratedInput)]
    if len(gens) != 1:
        raise ValueError("beam_search needs exactly one GeneratedInput")
    gen = gens[0]
    bs = L.BeamSearchDecoder(beam_size=beam_size, bos_id=bos_id,
                             eos_id=eos_id, max_len=max_length,
                             vocab_size=gen.size, name=name)
    g = _GroupCtx(bs, "beam")
    with bs.step():
        _group_stack.append(g)
        try:
            tok = bs.token()
            emb = L.embedding(
                tok, size=[gen.size, gen.embedding_size],
                param_attr=ParamAttr(name=gen.embedding_name))
            args = []
            for it in items:
                if isinstance(it, GeneratedInput):
                    args.append(emb)
                else:
                    args.append(bs.context(it.input))
            probs = step(*args)
            _resolve_memories(g)
            bs.set_probs(probs)
        finally:
            _group_stack.pop()
    ids, scores, lens = bs.outputs
    track_layer("__beam_search_predict__", ids)
    track_layer(name, ids)
    return ids


# ---------------------------------------------------------------------------
# v1 evaluators: recorded on the config; chunk F1 wires the chunk_eval op
# ---------------------------------------------------------------------------
def _record_evaluator(kind, **kw):
    from . import _state
    _state.evaluators.append({"kind": kind, **kw})


def sum_evaluator(input, name=None, weight=None, **kw):
    _record_evaluator("sum", name=name, input=input)


def classification_error_evaluator(input, label, name=None, **kw):
    _record_evaluator("classification_error", name=name, input=input,
                      label=label)


def chunk_evaluator(input, label, chunk_scheme, num_chunk_types, name=None,
                    **kw):
    """v1 chunk F1 (ChunkEvaluator.cpp) -> chunk_eval op outputs recorded on
    the config (precision/recall/F1 fetchable by the runner)."""
    label = _seq_label_layer(label)
    helper = LayerHelper("chunk_eval", name=name)
    outs = {nm: helper.create_variable_for_type_inference("float32")
            for nm in ("Precision", "Recall", "F1-Score")}
    counts = {nm: helper.create_variable_for_type_inference("int64")
              for nm in ("NumInferChunks", "NumLabelChunks",
                         "NumCorrectChunks")}
    helper.append_op(
        type="chunk_eval",
        inputs={"Inference": [input], "Label": [label]},
        outputs={**{k: [v] for k, v in outs.items()},
                 **{k: [v] for k, v in counts.items()}},
        attrs={"chunk_scheme": chunk_scheme,
               "num_chunk_types": num_chunk_types,
               "excluded_chunk_types": []})
    _record_evaluator("chunk", name=name, precision=outs["Precision"],
                      recall=outs["Recall"], f1=outs["F1-Score"])
    return outs["F1-Score"]


def seqtext_printer_evaluator(input, result_file=None, id_input=None,
                              dict_file=None, name=None, **kw):
    """v1 seqtext printer: recorded; the runner decodes ids via the dict
    and writes result_file (no side effects at config-build time)."""
    _record_evaluator("seqtext_printer", name=name, input=input,
                      id_input=id_input, dict_file=dict_file,
                      result_file=result_file)


# ---------------------------------------------------------------------------
# quick_start-surface helpers (layers.py maxid/pooling; networks.py
# sequence_conv_pool / bidirectional_lstm)
# ---------------------------------------------------------------------------
def maxid_layer(input, name=None, **kw):
    """v1 maxid (layers.py:1537): per-row argmax id."""
    out = L.argmax(input, axis=-1)
    return track_layer(name, out)


def pooling_layer(input, pooling_type=None, name=None, **kw):
    """v1 pooling over a sequence (layers.py:1700); default max."""
    ptype = pooling_type.ptype if pooling_type is not None else "max"
    out = L.sequence_pool(input, ptype)
    return track_layer(name, out)


def sequence_conv_pool(input, context_len, hidden_size, name=None,
                       context_start=None, pool_type=None, fc_act=None,
                       context_proj_param_attr=None, fc_param_attr=None,
                       **kw):
    """networks.py:312 text_conv_pool/sequence_conv_pool: context window
    conv + max pool over time.  ``fc_act`` defaults to Tanh like the
    reference's @wrap_act_default."""
    from . import _act_name
    from .. import nets
    out = nets.sequence_conv_pool(
        input, num_filters=hidden_size, filter_size=context_len,
        act=_act_name(fc_act) or "tanh",
        pool_type=(pool_type.ptype if pool_type is not None else "max"),
        param_attr=fc_param_attr)
    return track_layer(name, out)


def bidirectional_lstm(input, size, name=None, return_seq=False,
                       fwd_act=None, fwd_gate_act=None, fwd_state_act=None,
                       bwd_act=None, bwd_gate_act=None, bwd_state_act=None,
                       **kw):
    """networks.py:1310: forward + backward LSTM over the sequence;
    concat of last/first states (or the full sequences with
    return_seq=True)."""
    from . import _act_name
    fwd_proj = L.fc(input, size=size * 4, num_flatten_dims=2)
    fwd, _ = L.dynamic_lstm(
        fwd_proj, size=size * 4,
        gate_activation=_act_name(fwd_gate_act) or "sigmoid",
        cell_activation=_act_name(fwd_state_act) or "tanh",
        candidate_activation=_act_name(fwd_act) or "tanh")
    bwd_proj = L.fc(input, size=size * 4, num_flatten_dims=2)
    bwd, _ = L.dynamic_lstm(
        bwd_proj, size=size * 4, is_reverse=True,
        gate_activation=_act_name(bwd_gate_act) or "sigmoid",
        cell_activation=_act_name(bwd_state_act) or "tanh",
        candidate_activation=_act_name(bwd_act) or "tanh")
    if return_seq:
        out = L.concat([fwd, bwd], axis=-1)   # concat threads the @LEN
    else:
        out = L.concat([L.sequence_last_step(fwd),
                        L.sequence_first_step(bwd)], axis=-1)
    return track_layer(name, out)


def expand_layer(input, expand_as, name=None, **kw):
    """v1 expand_layer (layers.py:1571): broadcast per-sequence rows along
    another sequence's time dim."""
    return track_layer(name, L.sequence_expand(input, expand_as))


def scaling_layer(input, weight, name=None, **kw):
    """v1 scaling_layer (layers.py:2103): per-position scalar weight times
    the sequence's feature vectors."""
    return track_layer(name, L.elementwise_mul(input, weight))


def simple_attention(encoded_sequence, encoded_proj, decoder_state,
                     transform_param_attr=None, softmax_param_attr=None,
                     weight_act=None, name=None, **kw):
    """networks.py:1400 simple_attention (Bahdanau): project the decoder
    state, add to the per-position encoder projections, score with a
    sequence-softmaxed fc, and sum-pool the weighted encoder outputs into
    a context vector."""
    from . import _act_name
    name = name or unique_name.generate("attention")
    proj_size = encoded_proj.shape[-1]
    m = L.fc(decoder_state, size=proj_size, bias_attr=False,
             param_attr=transform_param_attr)
    expanded = L.sequence_expand(m, encoded_proj)
    combined = L.elementwise_add(expanded, encoded_proj)
    a = _act_name(weight_act)
    if a:
        combined = getattr(L, a)(combined)
    att = L.fc(combined, size=1, num_flatten_dims=2, bias_attr=False,
               param_attr=softmax_param_attr)
    weight = L.sequence_softmax(att)              # masked over true length
    scaled = L.elementwise_mul(encoded_sequence, weight)
    return track_layer(name, L.sequence_pool(scaled, "sum"))


def gru_step_layer(input, output_mem, size=None, act=None, gate_act=None,
                   name=None, param_attr=None, bias_attr=None, **kw):
    """v1 gru_step_layer (layers.py:3364): ONE GRU step inside a
    recurrent_group — input is the [B, 3H] projection, output_mem the
    previous hidden."""
    from . import _act_name
    size = size or input.shape[-1] // 3
    hidden, _, _ = L.gru_unit(
        input, output_mem, size * 3, param_attr=param_attr,
        bias_attr=bias_attr,
        activation=_act_name(act) or "tanh",
        gate_activation=_act_name(gate_act) or "sigmoid")
    return track_layer(name, hidden)


# ---------------------------------------------------------------------------
# thin v1 layer wrappers over existing ops (layers.py: power:2142,
# slope_intercept:5237, sum_to_one_norm:3288, cos_sim:2315, trans:2230,
# repeat:1914, seq_reshape:1980)
# ---------------------------------------------------------------------------
def power_layer(input, weight, name=None, **kw):
    """out = x ^ w with per-row scalar weight."""
    return track_layer(name, L.elementwise_pow(input, weight))


def slope_intercept_layer(input, slope=1.0, intercept=0.0, name=None, **kw):
    return track_layer(name, L.scale(input, scale=float(slope),
                                     bias=float(intercept)))


def sum_to_one_norm_layer(input, name=None, **kw):
    s = L.reduce_sum(input, dim=[-1], keep_dim=True)
    return track_layer(name, L.elementwise_div(input, s))


def cos_sim(a, b, scale=1, name=None, **kw):
    out = L.cos_sim(a, b)
    if scale != 1:
        out = L.scale(out, scale=float(scale))
    return track_layer(name, out)


def trans_layer(input, name=None, **kw):
    return track_layer(name, L.transpose(input, [1, 0]))


def repeat_layer(input, num_repeats, name=None, **kw):
    """Repeat each feature column num_repeats times ([B, D] -> [B, D*n])."""
    reps = [input] * num_repeats
    return track_layer(name, L.concat(reps, axis=1))


def seq_reshape_layer(input, reshape_size, name=None, **kw):
    return track_layer(name, L.sequence_reshape(input, reshape_size))


def print_layer(input, name=None, format=None, **kw):
    """v1 PrintLayer diagnostic: logs values at run time (print op)."""
    items = input if isinstance(input, (list, tuple)) else [input]
    helper = LayerHelper("print", name=name)
    for v in items:
        helper.append_op(type="print", inputs={"In": [v]}, outputs={},
                         attrs={"message": format or f"{v.name}:"})
    return items[0] if len(items) == 1 else items
