"""v1 layer_math (reference: trainer_config_helpers/layer_math.py):
unary math functions over layer outputs, plus the Variable arithmetic
operators the reference installs on LayerOutput (add/sub/mul with scalars
and layers) — used e.g. by the VAE demo's ``layer_math.exp(logvar) * 0.5``.

The operator overloads live on core Variable (core/program.py) so they
work for every front end, fluid-style included."""
from __future__ import annotations

from .. import layers as L

__all__ = ["exp", "log", "abs", "sigmoid", "tanh", "square", "relu",
           "sqrt", "reciprocal"]


def _unary(op_type):
    def fn(input, name=None):
        from ..layer_helper import LayerHelper
        helper = LayerHelper(op_type, name=name)
        out = helper.create_variable_for_type_inference(
            input.dtype, input.shape, lod_level=input.lod_level)
        helper.append_op(type=op_type, inputs={"X": [input]},
                         outputs={"Out": [out]})
        return out
    fn.__name__ = op_type
    return fn


exp = _unary("exp")
log = _unary("log")
abs = _unary("abs")          # noqa: A001  (mirrors the reference name)
sigmoid = _unary("sigmoid")
tanh = _unary("tanh")
square = _unary("square")
relu = _unary("relu")
sqrt = _unary("sqrt")
reciprocal = _unary("reciprocal")
