"""Deterministic testing utilities for the fault-tolerant runtime.

:mod:`paddle_tpu.testing.faultinject` is the seed-driven fault-injection
harness behind ``PADDLE_TPU_FAULT_SPEC`` — see that module for the spec
grammar and the registered injection sites.
"""
from . import faultinject

__all__ = ["faultinject"]
