"""Deterministic testing utilities for the fault-tolerant runtime.

:mod:`paddle_tpu.testing.faultinject` is the seed-driven fault-injection
harness behind ``PADDLE_TPU_FAULT_SPEC`` — see that module for the spec
grammar and the registered injection sites.

:mod:`paddle_tpu.testing.lockwatch` is the opt-in lock-order watchdog
behind ``PADDLE_TPU_LOCKWATCH`` — instrumented Lock/RLock/Condition
factories that turn a would-be deadlock into a deterministic typed
report (the runtime twin of ``analysis.concurrency``'s PT05x pass).
"""
from . import faultinject
from . import lockwatch

__all__ = ["faultinject", "lockwatch"]
