"""Runtime lock-order watchdog — the dynamic twin of the PT05x static
pass (:mod:`paddle_tpu.analysis.concurrency`).

The static pass sees lexical ``with`` nesting; this module sees what the
process *actually does*: an opt-in instrumented Lock/RLock/Condition that
records the process-wide acquisition-order graph by lock **class** (the
creation-site name passed to the factory, lockdep-style — not the
instance, so ten per-connection locks of one kind are one node) and, at
every acquire, checks the would-be edge against the graph **before
blocking**.  A cycle therefore surfaces as a deterministic
:class:`LockOrderViolation` naming both lock classes and both first-seen
acquisition stacks — instead of the 50/50 interleaving-dependent hang a
real inversion produces.  A held-too-long watchdog feeds the
``concurrency/*`` metrics on release.

Activation follows the PR 5 zero-overhead convention exactly
(:mod:`.faultinject`): the ``PADDLE_TPU_LOCKWATCH`` env var is read once
at import; when off, :func:`make_lock` / :func:`make_rlock` /
:func:`make_condition` return **plain** ``threading`` primitives — same
types, zero per-acquisition work, zero retrace risk — which is what the
tier-1 counter-delta + ``retrace_guard`` test pins.  Enable for a run::

    PADDLE_TPU_LOCKWATCH=1 python -m pytest tests/test_serving.py

Knobs:

* ``PADDLE_TPU_LOCKWATCH`` — truthy enables instrumentation.
* ``PADDLE_TPU_LOCKWATCH_HOLD_MS`` — held-too-long threshold for the
  ``concurrency/long_holds`` counter (default 1000).

Deliberately NOT wrapped: the metrics registry's own lock (lockwatch
writes metrics — wrapping it would recurse), the compile-cache lock and
the profiler trace lock (leaf infrastructure locks on import-time paths
the watchdog itself may traverse).
"""
from __future__ import annotations

import os
import threading
import time
import traceback
from typing import Dict, List, Optional, Tuple

__all__ = [
    "ENABLED", "enabled", "make_lock", "make_rlock", "make_condition",
    "LockOrderViolation", "graph", "violations", "reset",
    "hold_threshold_ms",
]


def _env_on(name: str) -> bool:
    return os.environ.get(name, "").strip().lower() not in (
        "", "0", "false", "off", "no")


#: resolved ONCE at import — the off path must stay compiled-out cheap,
#: so per-call env reads are off the table (same contract as faultinject)
ENABLED = _env_on("PADDLE_TPU_LOCKWATCH")

_DEFAULT_HOLD_MS = 1000.0


def enabled() -> bool:
    """Is lockwatch instrumentation active in this process?"""
    return ENABLED


def hold_threshold_ms() -> float:
    try:
        return float(os.environ.get("PADDLE_TPU_LOCKWATCH_HOLD_MS",
                                    _DEFAULT_HOLD_MS))
    except ValueError:
        return _DEFAULT_HOLD_MS


class LockOrderViolation(RuntimeError):
    """A lock acquisition would create an ordering cycle.

    Raised by the acquiring thread BEFORE it blocks, so the process
    reports the inversion deterministically instead of deadlocking when
    the interleaving happens to interleave.  Carries both lock-class
    names and both acquisition stacks: the current one (this thread,
    ``holding`` -> ``acquiring``) and the first-seen stack that recorded
    the reverse edge (``acquiring`` -> ... -> ``holding``).
    """

    def __init__(self, acquiring: str, holding: str,
                 current_stack: str, reverse_stack: str,
                 path: Tuple[str, ...]):
        self.acquiring = acquiring
        self.holding = holding
        self.current_stack = current_stack
        self.reverse_stack = reverse_stack
        self.path = path
        super().__init__(self.report())

    def report(self) -> str:
        chain = " -> ".join(self.path)
        return (
            f"lock-order violation: acquiring {self.acquiring!r} while "
            f"holding {self.holding!r}, but the acquisition graph "
            f"already orders {chain} — two threads taking these locks "
            f"in opposite order deadlock.\n"
            f"--- this thread (holds {self.holding!r}, wants "
            f"{self.acquiring!r}):\n{self.current_stack}"
            f"--- first-seen reverse ordering ({self.acquiring!r} "
            f"before {self.holding!r}):\n{self.reverse_stack}")


# ---------------------------------------------------------------------------
# Process-wide state (only touched when ENABLED)
# ---------------------------------------------------------------------------
_glock = threading.Lock()        # guards _edges/_violations (leaf lock)
#: lock-class edge -> first-seen acquisition stack: _edges[a][b] is set
#: when some thread acquired class b while holding class a
_edges: Dict[str, Dict[str, str]] = {}
_violations: List[LockOrderViolation] = []
_tls = threading.local()


def _held() -> List[Tuple[str, int, float]]:
    """This thread's hold stack: (class name, instance id, t_acquire)."""
    try:
        return _tls.held
    except AttributeError:
        _tls.held = []
        return _tls.held


def _reachable(src: str, dst: str) -> Optional[Tuple[str, ...]]:
    """Path src -> ... -> dst in the edge graph, or None.  Caller holds
    ``_glock``."""
    stack = [(src, (src,))]
    seen = {src}
    while stack:
        node, path = stack.pop()
        if node == dst:
            return path
        for nxt in _edges.get(node, {}):
            if nxt not in seen:
                seen.add(nxt)
                stack.append((nxt, path + (nxt,)))
    return None


def _metrics():
    # local import: lockwatch must not pull the observability package
    # into processes that never enable it
    from ..observability import metrics as _m
    return _m


def _pre_acquire(name: str, inst: int, reentrant: bool):
    """Order check + edge recording; runs BEFORE blocking on the lock."""
    held = _held()
    if any(h_inst == inst for (_n, h_inst, _t) in held):
        if reentrant:
            return                      # RLock re-entry: no new ordering
        raise LockOrderViolation(
            name, name, "".join(traceback.format_stack(limit=16)),
            "(same thread, same lock instance)", (name, name))
    held_names = [n for (n, _i, _t) in held
                  if n != name]         # same class doesn't order itself
    if not held_names:
        return
    with _glock:
        for h in held_names:
            path = _reachable(name, h)
            if path is not None:
                reverse = _edges.get(path[0], {}).get(path[1], "<?>")
                v = LockOrderViolation(
                    name, h,
                    "".join(traceback.format_stack(limit=16)),
                    reverse, path + (name,))
                _violations.append(v)
                try:
                    _metrics().inc_counter(
                        "concurrency/order_violations")
                except ImportError:
                    pass        # interpreter shutdown mid-teardown
                raise v
        new_edge = False
        for h in held_names:
            d = _edges.setdefault(h, {})
            if name not in d:
                d[name] = "".join(traceback.format_stack(limit=16))
                new_edge = True
        if new_edge:
            try:
                _metrics().set_gauge(
                    "concurrency/order_edges",
                    sum(len(d) for d in _edges.values()))
            except ImportError:
                pass            # interpreter shutdown mid-teardown


def _post_acquire(name: str, inst: int):
    _held().append((name, inst, time.monotonic()))


def _pre_release(name: str, inst: int):
    held = _held()
    for i in range(len(held) - 1, -1, -1):
        if held[i][1] == inst:
            _n, _i, t0 = held.pop(i)
            held_ms = (time.monotonic() - t0) * 1000.0
            try:
                m = _metrics()
                m.observe_hist("concurrency/lock_held_ms", held_ms)
                if held_ms >= hold_threshold_ms():
                    m.inc_counter("concurrency/long_holds")
            except ImportError:
                pass            # interpreter shutdown mid-teardown
            return


class _WatchedLock:
    """Instrumented mutex; context-manager and acquire/release compatible
    with ``threading.Lock`` / ``RLock``."""

    def __init__(self, name: str, reentrant: bool):
        self._name = name
        self._reentrant = reentrant
        self._raw = threading.RLock() if reentrant else threading.Lock()
        self._depth = 0          # RLock re-entry depth (owner-only write)

    # -- lock protocol ----------------------------------------------------
    def acquire(self, blocking: bool = True, timeout: float = -1):
        _pre_acquire(self._name, id(self), self._reentrant)
        ok = self._raw.acquire(blocking, timeout)
        if ok:
            if self._reentrant:
                self._depth += 1
                if self._depth == 1:
                    _post_acquire(self._name, id(self))
            else:
                _post_acquire(self._name, id(self))
        return ok

    def release(self):
        if self._reentrant:
            self._depth -= 1
            if self._depth == 0:
                _pre_release(self._name, id(self))
        else:
            _pre_release(self._name, id(self))
        self._raw.release()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    def locked(self):
        return self._raw.locked() if hasattr(self._raw, "locked") \
            else self._depth > 0

    def __repr__(self):
        kind = "RLock" if self._reentrant else "Lock"
        return f"<lockwatch.{kind} {self._name!r}>"


class _WatchedCondition:
    """Condition bound to a watched lock: delegates the wait machinery to
    a real ``threading.Condition`` built on the RAW lock (so
    ``_is_owned``/``_release_save`` see a native primitive), while the
    hold bookkeeping goes through the watched wrapper.

    ``wait`` re-acquires WITHOUT the cycle re-check: the thread held this
    lock before waiting, so its ordering edges are already recorded, and
    re-checking after the wakeup would re-raise on edges the pre-wait
    acquire legitimately created.
    """

    def __init__(self, lock: _WatchedLock):
        self._wlock = lock
        self._cond = threading.Condition(lock._raw)

    # the lock protocol proxies through the watched lock
    def acquire(self, *a, **kw):
        return self._wlock.acquire(*a, **kw)

    def release(self):
        self._wlock.release()

    def __enter__(self):
        self._wlock.acquire()
        return self

    def __exit__(self, *exc):
        self._wlock.release()
        return False

    def wait(self, timeout: Optional[float] = None):
        _pre_release(self._wlock._name, id(self._wlock))
        if self._wlock._reentrant:
            depth, self._wlock._depth = self._wlock._depth, 0
        try:
            return self._cond.wait(timeout)
        finally:
            if self._wlock._reentrant:
                self._wlock._depth = depth
            _post_acquire(self._wlock._name, id(self._wlock))

    def wait_for(self, predicate, timeout: Optional[float] = None):
        # manual re-implementation so each park goes through wait()'s
        # hold bookkeeping
        endtime = None
        result = predicate()
        while not result:
            if timeout is not None:
                if endtime is None:
                    endtime = time.monotonic() + timeout
                waittime = endtime - time.monotonic()
                if waittime <= 0:
                    break
                self.wait(waittime)
            else:
                self.wait()
            result = predicate()
        return result

    def notify(self, n: int = 1):
        self._cond.notify(n)

    def notify_all(self):
        self._cond.notify_all()

    def __repr__(self):
        return f"<lockwatch.Condition on {self._wlock._name!r}>"


# ---------------------------------------------------------------------------
# Factories — THE api call sites use.  Off: plain threading primitives
# (type identity pinned by tests), zero bookkeeping ever allocated.
# ---------------------------------------------------------------------------
def make_lock(name: str):
    """A mutex named for ordering purposes; plain ``threading.Lock`` when
    lockwatch is off."""
    if not ENABLED:
        return threading.Lock()
    return _WatchedLock(name, reentrant=False)


def make_rlock(name: str):
    """Reentrant variant of :func:`make_lock`."""
    if not ENABLED:
        return threading.RLock()
    return _WatchedLock(name, reentrant=True)


def make_condition(name: str, lock=None):
    """A condition variable on ``lock`` (or a fresh named lock).

    When lockwatch is on and ``lock`` is a watched lock, the condition
    shares its graph node; when off this is exactly
    ``threading.Condition(lock)``.
    """
    if not ENABLED:
        return threading.Condition(lock)
    if lock is None:
        lock = _WatchedLock(name, reentrant=False)
    if isinstance(lock, _WatchedLock):
        return _WatchedCondition(lock)
    # a raw lock slipped in (e.g. created before enabling): fall back to
    # the plain primitive rather than mis-track ownership
    return threading.Condition(lock)


# ---------------------------------------------------------------------------
# Introspection (tests, stats CLI)
# ---------------------------------------------------------------------------
def graph() -> Dict[str, Tuple[str, ...]]:
    """The current acquisition-order graph: {held: (acquired-after, ...)}."""
    with _glock:
        return {a: tuple(sorted(d)) for a, d in sorted(_edges.items())}


def violations() -> List[LockOrderViolation]:
    with _glock:
        return list(_violations)


def reset():
    """Clear the process-wide graph + violation list (tests only)."""
    with _glock:
        _edges.clear()
        _violations.clear()
