"""Deterministic fault injection behind ``PADDLE_TPU_FAULT_SPEC``.

Every failure path the fault-tolerant runtime promises to survive has an
**injection site** — a named host-side hook on the real code path — so the
chaos suite can make "the master dropped the connection at call 3" or
"the process was preempted at step 7" *reproducible facts* instead of
rare coincidences.  With no spec configured the harness is compiled out
to a single module-attribute check (``if faultinject.ENABLED:``) at each
site: the off path does no parsing, no locking, no counting — pinned by
the same counter-delta tier-1 test that guards the observability layer.

Spec grammar (``;``-separated entries)::

    PADDLE_TPU_FAULT_SPEC = "<site>@<when>=<action>[;...]"

* ``site``  — dotted site name (see table below).
* ``when``  — ``N`` (integer): fire when the site's *index* equals N.
  Sites called with an explicit ``index`` (e.g. the trainer's global
  batch counter) match on that index, so a resumed run that starts past
  N does NOT re-trigger; sites without a natural index match on their
  1-based per-process hit count.  ``*`` fires on every hit.
* ``action`` — interpreted by the site.  Generic actions every site
  understands through :func:`raise_for`: ``error`` (InjectedFault),
  ``transient`` (TransientDispatchError — classified retryable), ``drop``
  (ConnectionError).  Site-specific actions: ``truncate`` (ckpt.write:
  torn shard file), ``preempt`` (trainer.step: graceful preemption flag,
  as if SIGTERM arrived; an error when train() has no checkpoint_dir),
  ``sigterm`` (trainer.step: a real SIGTERM to this process), ``kill``
  (trainer.step: a real SIGKILL to this process — no handler, no
  emergency checkpoint, returncode ``-9`` exactly like hard preemption,
  which supervisors treat as relaunchable signal death).

Registered sites:

========================  ==================================================
``trainer.step``          per completed batch in ``trainer.SGD.train``
                          (index = global batch counter)
``reader.item``           per batch pulled from the reader (index = global
                          batch counter) — fires *before* the step runs
``executor.dispatch``     per compiled-step dispatch in ``Executor.run`` /
                          ``run_steps`` (inside the retry rim)
``master.call``           per ``MasterClient`` RPC attempt (inside the
                          retry rim; ``drop`` closes the live socket too)
``ckpt.write``            per shard file written by ``CheckpointManager``
                          (``truncate`` corrupts the file after its md5 is
                          recorded, simulating a torn write)
``serving.request``       per request admitted to ``serving.Server.submit``
                          (hit-count indexed).  ``delay[:ms]`` sleeps
                          (default 50 ms) before admission — a slow-ingress
                          simulation; ``drop`` raises ConnectionError at
                          the admission rim
``serving.dispatch``      per coalesced batch dispatched by the serving
                          runtime (inside its retry rim).  ``transient``
                          retries per the server's policy; ``fatal``
                          raises :class:`InjectedFault` (classified fatal
                          — feeds the per-model circuit breaker)
``serving.decode_step``   per decode-pool token-step dispatch
                          (``serving.decode.DecodeRuntime``; hit-count
                          indexed; fires inside the retry rim BEFORE the
                          executor call, so the donated KV slabs are
                          untouched when it fires).  ``transient``
                          retries per the pool's policy without
                          corrupting surviving slots; ``fatal`` raises
                          :class:`InjectedFault` — the affected ACTIVE
                          sequences complete with typed errors, queued
                          requests survive, and the breaker counts it
``tuning.trial``          per autotuner trial (``tuning.search.run_trial``;
                          hit-count indexed).  ``fail`` makes the trial's
                          measurement raise (recorded ``failed``);
                          ``timeout`` makes it overrun its budget
                          (recorded ``timeout``) — both INSIDE the
                          containment rim, so the search must survive
``elastic.worker``        per completed batch in an elastic worker
                          (``distributed.elastic.ElasticWorker``; index =
                          the worker's global batch counter, restored
                          across relaunches).  ``kill`` sends the worker
                          a REAL SIGKILL (hard death mid-pass: no
                          handler, no emergency checkpoint — the chaos
                          suite's zero-task-loss case); ``preempt``
                          requests a graceful preemption exactly like a
                          SIGTERM (emergency checkpoint at the boundary,
                          exit 75)
``master.heartbeat``      per heartbeat SENT by an elastic worker
                          (hit-count indexed).  ``drop`` loses the
                          heartbeat on the wire (the worker swallows the
                          injected ConnectionError, best-effort
                          semantics) — enough consecutive drops and the
                          coordinator sees lease staleness, which is the
                          membership-change trigger being tested
``sparse.push``           per gradient push into a host sparse table
                          (``sparse.SparseSession``; hit-count indexed;
                          fires BEFORE the update applies, inside the
                          session's retry rim).  ``drop`` loses the push
                          on the wire-analog: with a retry policy it is
                          retried (exactly-once — nothing mutated before
                          the site), without one it raises — a dropped
                          push is never silent (the grads exist nowhere
                          else)
``pserver.rpc``           per request frame RECEIVED by a pserver shard
                          (``sparse.pserver.PServer``; hit-count
                          indexed, before dispatch).  ``drop`` closes
                          the connection mid-exchange — the client sees
                          a torn frame (typed ``WireTruncatedError``)
                          and its retry rim reconnects and replays;
                          ``transient`` answers a typed retryable error
                          reply instead of the result
``pserver.shard``         per APPLIED push on a pserver shard (index =
                          the shard's persisted applied-push counter,
                          restored from checkpoint/chain backup — the
                          ``elastic.worker`` restored-counter
                          convention, so a ``kill`` fired in one life
                          never re-fires after relaunch).  ``kill``
                          SIGKILLs the shard process AFTER the push is
                          applied and chain-replicated but BEFORE the
                          client ack — the zero-acked-push-loss case
``ckpt.delta``            per file written by a DELTA commit (sparse
                          dirty-row pieces and dense chunk patches;
                          full commits keep firing ``ckpt.write``).
                          ``truncate`` tears the file after its md5 is
                          recorded — restore must reject the tip and
                          fall back to the last durable prefix of the
                          chain; ``kill`` SIGKILLs the process
                          mid-chain (no handler, no retraction — the
                          torn-chain recovery case)
========================  ==================================================

Every firing increments the ``fault/injected`` counter and emits a
``fault`` JSONL event, so an injected run's history is visible to
``python -m paddle_tpu stats``.
"""
from __future__ import annotations

import os
import threading
from typing import Dict, List, Optional, Tuple

from ..faults import InjectedFault, TransientDispatchError

__all__ = [
    "ENABLED", "configure", "clear", "active_spec", "check", "raise_for",
    "hits", "fired", "KNOWN_SITES",
]

KNOWN_SITES = ("trainer.step", "reader.item", "executor.dispatch",
               "master.call", "ckpt.write", "serving.request",
               "serving.dispatch", "serving.decode_step", "tuning.trial",
               "elastic.worker", "master.heartbeat", "sparse.push",
               "pserver.rpc", "pserver.shard", "ckpt.delta")

# THE zero-overhead gate: call sites guard every hook with
# ``if faultinject.ENABLED:`` — one attribute load when off.
ENABLED = False

_lock = threading.Lock()
_entries: List[Tuple[str, Optional[int], str]] = []   # (site, when, action)
_hit_counts: Dict[str, int] = {}
_fired_counts: Dict[str, int] = {}
_spec_text = ""


def _parse(spec: str) -> List[Tuple[str, Optional[int], str]]:
    entries = []
    for raw in spec.split(";"):
        raw = raw.strip()
        if not raw:
            continue
        head, sep, action = raw.partition("=")
        if not sep or not action:
            raise ValueError(
                f"fault spec entry {raw!r}: want site@when=action")
        site, sep, when_s = head.partition("@")
        site = site.strip()
        if not sep or not site:
            raise ValueError(
                f"fault spec entry {raw!r}: want site@when=action")
        when_s = when_s.strip()
        if when_s == "*":
            when: Optional[int] = None
        else:
            try:
                when = int(when_s)
            except ValueError:
                raise ValueError(
                    f"fault spec entry {raw!r}: when must be an integer "
                    f"or '*', got {when_s!r}")
        entries.append((site, when, action.strip()))
    return entries


def configure(spec: str):
    """Parse and activate a fault spec (replaces any active one; resets
    all hit counters).  An empty spec is equivalent to :func:`clear`."""
    global ENABLED, _entries, _spec_text
    parsed = _parse(spec)
    with _lock:
        _entries = parsed
        _spec_text = spec
        _hit_counts.clear()
        _fired_counts.clear()
        ENABLED = bool(parsed)


def clear():
    """Deactivate injection entirely (the default state)."""
    global ENABLED, _entries, _spec_text
    with _lock:
        _entries = []
        _spec_text = ""
        _hit_counts.clear()
        _fired_counts.clear()
        ENABLED = False


def active_spec() -> str:
    return _spec_text


def hits(site: str) -> int:
    """Times ``site`` was checked since :func:`configure` (counter-indexed
    sites only advance this when called without an explicit index)."""
    with _lock:
        return _hit_counts.get(site, 0)


def fired(site: str) -> int:
    """Times an injection actually fired at ``site``."""
    with _lock:
        return _fired_counts.get(site, 0)


def check(site: str, index: Optional[int] = None) -> Optional[str]:
    """Return the action to inject at this hit of ``site``, or None.

    Only call behind an ``if faultinject.ENABLED:`` guard — this function
    takes the lock and counts, which is exactly the work the off path
    must not do.  ``index``: the site's natural position (global batch
    counter etc.); without one, the 1-based per-process hit count is the
    match key.
    """
    with _lock:
        if not _entries:
            return None
        if index is None:
            index = _hit_counts.get(site, 0) + 1
            _hit_counts[site] = index
        action = None
        for s, when, a in _entries:
            if s == site and (when is None or when == int(index)):
                action = a
                break
        if action is None:
            return None
        _fired_counts[site] = _fired_counts.get(site, 0) + 1
    _record(site, int(index), action)
    return action


def _record(site: str, index: int, action: str):
    # cold path (an injection is firing): unconditional registry write +
    # JSONL event so the fault history survives into `stats`
    from ..observability import emit_event, inc_counter
    inc_counter("fault/injected")
    emit_event("fault", event="injected", site=site, index=index,
               action=action)


def raise_for(action: str, site: str, index: Optional[int] = None):
    """Raise the exception a generic action maps to.  Call sites handle
    their site-specific actions FIRST and route everything else here, so
    an action this function does not recognize is a spec mistake (typo,
    or a site-specific action aimed at the wrong site) — it raises
    ValueError rather than silently no-opping after :func:`check` already
    counted the injection as fired."""
    at = f"{site}" + (f"#{index}" if index is not None else "")
    if action == "error":
        raise InjectedFault(f"injected fault at {at}")
    if action == "transient":
        raise TransientDispatchError(f"injected transient fault at {at}")
    if action == "drop":
        raise ConnectionError(f"injected connection drop at {at}")
    raise ValueError(
        f"fault spec: action {action!r} is not understood at site {at} "
        f"(generic actions: error/transient/drop; site-specific actions "
        f"must target their own site)")


# Environment activation: one parse at import.  configure()/clear() from
# tests override freely afterwards.
_env_spec = os.environ.get("PADDLE_TPU_FAULT_SPEC", "")
if _env_spec:
    configure(_env_spec)
