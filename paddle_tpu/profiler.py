"""Profiling & timing utilities.

Reference analogs: v1 `Stat`/`REGISTER_TIMER` per-layer timers
(utils/Stat.h:63,114,230 printed per log period) and fluid's `cuda_profiler`
nvprof context manager (fluid/profiler.py:19-52).  TPU-native: jax.profiler
traces (viewable in TensorBoard/XProf) + host-side step timers.
"""
from __future__ import annotations

import collections
import contextlib
import time
from typing import Dict

import jax


@contextlib.contextmanager
def profiler(output_dir: str = "/tmp/paddle_tpu_trace", state=None,
             sorted_key=None):
    """Trace the enclosed steps with jax.profiler (cuda_profiler analog)."""
    jax.profiler.start_trace(output_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


cuda_profiler = profiler  # reference-name alias


class Stat:
    """Accumulating named timer (utils/Stat.h StatSet analog)."""

    def __init__(self):
        self._totals: Dict[str, float] = collections.defaultdict(float)
        self._counts: Dict[str, int] = collections.defaultdict(int)

    @contextlib.contextmanager
    def timer(self, name: str):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            dt = time.perf_counter() - t0
            self._totals[name] += dt
            self._counts[name] += 1

    def report(self) -> str:
        lines = ["======= StatSet ======="]
        for name in sorted(self._totals, key=lambda n: -self._totals[n]):
            tot = self._totals[name]
            cnt = self._counts[name]
            lines.append(f"  {name}: total={tot*1e3:.2f}ms count={cnt} "
                         f"avg={tot/cnt*1e3:.3f}ms")
        return "\n".join(lines)

    def reset(self):
        self._totals.clear()
        self._counts.clear()


_global_stat = Stat()


def global_stat() -> Stat:
    return _global_stat


@contextlib.contextmanager
def timer(name: str):
    """REGISTER_TIMER analog on the global StatSet."""
    with _global_stat.timer(name):
        yield


# ---------------------------------------------------------------------------
# Compile-time telemetry (core/compile_cache.py)
# ---------------------------------------------------------------------------
def compile_stats():
    """The global :class:`~paddle_tpu.core.compile_cache.CompileStats`:
    per-fingerprint trace/lower/compile wall times, cache hit/miss/evict
    counters, and the retrace detector
    (``compile_stats().assert_no_retrace()``).  The compile-time analog of
    :func:`global_stat` — a cold start's cost lives here, not in step
    timers."""
    from .core import compile_cache
    return compile_cache.stats()


def compile_report() -> str:
    """Human-readable compile telemetry (StatSet-style report)."""
    return compile_stats().report()


class StepTimer:
    """Per-step wall-clock with warmup discard, for benchmarks."""

    def __init__(self, warmup: int = 2):
        self.warmup = warmup
        self.times = []
        self._t = None
        self._step = 0

    def start(self):
        self._t = time.perf_counter()

    def stop(self):
        dt = time.perf_counter() - self._t
        self._step += 1
        if self._step > self.warmup:
            self.times.append(dt)
        return dt

    @property
    def mean(self):
        return sum(self.times) / max(len(self.times), 1)
