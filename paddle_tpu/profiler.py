"""Profiling & timing utilities.

Reference analogs: v1 `Stat`/`REGISTER_TIMER` per-layer timers
(utils/Stat.h:63,114,230 printed per log period) and fluid's `cuda_profiler`
nvprof context manager (fluid/profiler.py:19-52).  TPU-native: jax.profiler
traces (viewable in TensorBoard/XProf) + host-side step timers.

This module is the human-facing surface of the observability layer
(paddle_tpu.observability): :func:`report` renders the merged StatSet +
CompileStats + Metrics view, :func:`metrics_snapshot` the structured one.
"""
from __future__ import annotations

import collections
import contextlib
import threading
import time
from typing import Dict

import jax

_trace_lock = threading.Lock()
_trace_depth = 0
_trace_started = False


@contextlib.contextmanager
def profiler(output_dir: str = "/tmp/paddle_tpu_trace", state=None,
             sorted_key=None):
    """Trace the enclosed steps with jax.profiler (cuda_profiler analog).

    ``state`` and ``sorted_key`` are accepted for reference API
    compatibility (fluid/profiler.py took 'GPU'/'total' etc.) and are
    IGNORED: jax.profiler always traces both host and device, and sorting
    belongs to the TensorBoard/XProf viewer, not the collector.

    Reentrant: nested scopes are no-op inner scopes — one trace session
    spans the outermost ``with`` (jax.profiler.start_trace raises if a
    trace is already active, so without this guard nesting crashed).
    """
    del state, sorted_key            # reference-compat, ignored (see doc)
    global _trace_depth, _trace_started
    with _trace_lock:
        _trace_depth += 1
        outermost = _trace_depth == 1
    if outermost:
        try:
            jax.profiler.start_trace(output_dir)
            with _trace_lock:
                _trace_started = True
        except BaseException:
            with _trace_lock:
                _trace_depth -= 1
            raise
    try:
        yield
    finally:
        # the LAST exiter stops the session (overlapping scopes from
        # different threads ride one session; outermost-exits-first must
        # not kill the trace under a still-active inner scope)
        with _trace_lock:
            _trace_depth -= 1
            stop = _trace_depth == 0 and _trace_started
            if stop:
                _trace_started = False
        if stop:
            jax.profiler.stop_trace()


cuda_profiler = profiler  # reference-name alias


class Stat:
    """Accumulating named timer (utils/Stat.h StatSet analog).

    Thread-safe: pipeline worker threads and the run_pipelined staging
    thread time into the same instance as the dispatch thread.  A
    ``reset()`` racing a live ``timer()`` scope is well-defined — the
    in-flight scope records into the fresh epoch when it closes, and
    ``report()`` renders a consistent snapshot either way."""

    def __init__(self):
        self._lock = threading.Lock()
        self._totals: Dict[str, float] = collections.defaultdict(float)
        self._counts: Dict[str, int] = collections.defaultdict(int)

    @contextlib.contextmanager
    def timer(self, name: str):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            dt = time.perf_counter() - t0
            with self._lock:
                self._totals[name] += dt
                self._counts[name] += 1

    def report(self) -> str:
        with self._lock:
            totals = dict(self._totals)
            counts = dict(self._counts)
        lines = ["======= StatSet ======="]
        for name in sorted(totals, key=lambda n: -totals[n]):
            tot = totals[name]
            cnt = max(counts.get(name, 0), 1)
            lines.append(f"  {name}: total={tot*1e3:.2f}ms count={cnt} "
                         f"avg={tot/cnt*1e3:.3f}ms")
        return "\n".join(lines)

    def reset(self):
        with self._lock:
            self._totals.clear()
            self._counts.clear()


_global_stat = Stat()


def global_stat() -> Stat:
    return _global_stat


@contextlib.contextmanager
def timer(name: str):
    """REGISTER_TIMER analog on the global StatSet."""
    with _global_stat.timer(name):
        yield


# ---------------------------------------------------------------------------
# Compile-time telemetry (core/compile_cache.py)
# ---------------------------------------------------------------------------
def compile_stats():
    """The global :class:`~paddle_tpu.core.compile_cache.CompileStats`:
    per-fingerprint trace/lower/compile wall times, cache hit/miss/evict
    counters, and the retrace detector
    (``compile_stats().assert_no_retrace()``).  The compile-time analog of
    :func:`global_stat` — a cold start's cost lives here, not in step
    timers."""
    from .core import compile_cache
    return compile_cache.stats()


def compile_report() -> str:
    """Human-readable compile telemetry (StatSet-style report)."""
    return compile_stats().report()


# ---------------------------------------------------------------------------
# Merged observability surface (paddle_tpu.observability)
# ---------------------------------------------------------------------------
def metrics_snapshot() -> dict:
    """Structured merged snapshot: registry metrics + compile counters +
    per-device memory (see observability.export.metrics_snapshot)."""
    from .observability import metrics_snapshot as _snap
    return _snap()


def report() -> str:
    """ONE merged human-readable view: host-side StatSet timers, compile
    telemetry, and the observability metrics registry — the v1
    ``printAllStatus`` every ``log_period`` analog (the trainer emits this
    via observability.maybe_periodic_report)."""
    from . import observability
    return "\n".join([_global_stat.report(), compile_report(),
                      observability.report()])


class StepTimer:
    """Per-step wall-clock with warmup discard, for benchmarks."""

    def __init__(self, warmup: int = 2):
        self.warmup = warmup
        self.times = []
        self._t = None
        self._step = 0

    def start(self):
        self._t = time.perf_counter()

    def stop(self):
        dt = time.perf_counter() - self._t
        self._step += 1
        if self._step > self.warmup:
            self.times.append(dt)
        return dt

    @property
    def mean(self):
        return sum(self.times) / max(len(self.times), 1)
