"""User utilities (reference: python/paddle/utils/ — dump_config.py,
make_model_diagram.py, merge_model.py, plotcurve.py; image_util/
preprocess_img are subsumed by `paddle_tpu.image` + `reader.xmap_readers`,
torch2paddle/predefined_net were one-off migration glue).

Each helper here is the TPU-native equivalent of one reference script,
operating on Programs / v1 configs instead of protobufs."""
from __future__ import annotations

import json
import os
import re
import tarfile
import tempfile

import numpy as np

__all__ = ["dump_config", "make_model_diagram", "merge_model",
           "load_merged_model", "plotcurve", "load_torch_state_dict"]


def dump_config(config_path, config_args=None, as_json=True):
    """Parse a v1 config file and return its full Program structure
    (utils/dump_config.py: parse_config + print the TrainerConfig proto —
    here the Program's dict serialization plays the proto's role)."""
    from .trainer_config_helpers import load_v1_config

    cfg = load_v1_config(config_path, **(config_args or {}))
    d = cfg.main_program.to_dict()
    return json.dumps(d, indent=1, default=str) if as_json else d


def make_model_diagram(config_path=None, program=None, dot_path=None,
                       config_args=None):
    """DOT diagram of a model (utils/make_model_diagram.py).  Accepts a
    v1 config path or a Program directly; returns the DOT source (and
    writes it to ``dot_path`` if given)."""
    from .net_drawer import draw_graph

    if program is None:
        from .trainer_config_helpers import load_v1_config
        program = load_v1_config(config_path,
                                 **(config_args or {})).main_program
    return draw_graph(program, path=dot_path)


def merge_model(output_file, program=None, scope=None):
    """Merge model structure + parameters into ONE deployable file
    (utils/merge_model.py merge_v2_model: config proto + Parameters →
    single binary).  Format: a .tar.gz holding ``program.json`` (the IR)
    and ``params.npz`` (every persistable scope array)."""
    from .core.program import default_main_program
    from .core.scope import global_scope

    program = program or default_main_program()
    scope = scope or global_scope()
    persistable = {v.name for b in program.blocks
                   for v in b.vars.values() if v.persistable}
    params = {n: np.asarray(scope.get(n)) for n in sorted(persistable)
              if scope.has(n)}
    with tempfile.TemporaryDirectory() as td:
        pj = os.path.join(td, "program.json")
        with open(pj, "w") as f:
            json.dump(program.to_dict(), f)
        pp = os.path.join(td, "params.npz")
        np.savez(pp, **params)
        tmp = output_file + ".part"
        with tarfile.open(tmp, "w:gz") as tf:
            tf.add(pj, arcname="program.json")
            tf.add(pp, arcname="params.npz")
        os.replace(tmp, output_file)
    return output_file


def load_merged_model(path, scope=None):
    """Load a `merge_model` artifact: returns the Program and installs the
    parameters into ``scope`` (default global scope)."""
    import io as _io

    from .core.program import Program
    from .core.scope import global_scope

    scope = scope or global_scope()
    with tarfile.open(path, "r:gz") as tf:
        prog = Program.from_dict(json.load(tf.extractfile("program.json")))
        blob = tf.extractfile("params.npz").read()
    arrs = np.load(_io.BytesIO(blob))
    for n in arrs.files:
        scope.set(n, arrs[n])
    return prog


def plotcurve(log_lines, key="cost", output_path=None):
    """Parse a training log into (pass_ids, values) for metric ``key``
    and optionally plot it (utils/plotcurve.py: gnuplot the
    'Pass N ... cost=X' lines; the output file was an argument there
    too).  Accepts an iterable of lines or a file path; returns the
    parsed arrays; writes a plot only when ``output_path`` is given
    (requires matplotlib)."""
    if isinstance(log_lines, str):
        with open(log_lines) as f:
            log_lines = f.readlines()
    pat = re.compile(
        r"Pass[= ](\d+).*?" + re.escape(key) + r"[= ]([0-9.eE+-]+)",
        re.IGNORECASE)
    ids, vals = [], []
    for line in log_lines:
        m = pat.search(line)
        if not m:
            continue
        try:
            v = float(m.group(2))
        except ValueError:          # malformed value (e.g. 'cost=...')
            continue
        ids.append(int(m.group(1)))
        vals.append(v)
    if output_path is not None:
        import matplotlib
        matplotlib.use("Agg")
        import matplotlib.pyplot as plt
        fig = plt.figure()
        plt.plot(ids, vals, marker="o")
        plt.xlabel("pass")
        plt.ylabel(key)
        fig.savefig(output_path)
        plt.close(fig)
    return np.asarray(ids), np.asarray(vals)


def load_torch_state_dict(state_dict, name_map, scope=None,
                          transpose_linear=True):
    """Import torch weights into scope parameters (the
    utils/torch2paddle.py role — that script converted torch-serialized
    models into v1 parameter files; here the unit of exchange is the
    modern ``state_dict``).

    ``name_map``: {torch_key: paddle_param_name} or
    {torch_key: (paddle_param_name, transpose_bool)} for explicit
    control.  Without an explicit flag, a 2-D tensor transposes when its
    shape only matches the target transposed (torch nn.Linear stores
    [out, in]; fc expects [in, out]); a SQUARE 2-D tensor is ambiguous
    and requires the explicit form (silently guessing would import
    numerically wrong weights).  Shapes are validated; dtypes cast to
    the existing parameter's.  Returns the imported parameter names.
    """
    from .core.scope import global_scope

    scope = global_scope() if scope is None else scope
    done = []
    for tkey, spec in name_map.items():
        if tkey not in state_dict:
            raise KeyError(f"torch state_dict has no key {tkey!r}")
        pname, transpose = (spec if isinstance(spec, (tuple, list))
                            else (spec, None))
        t = state_dict[tkey]
        arr = np.asarray(t.detach().cpu().numpy()
                         if hasattr(t, "detach") else t)
        cur = np.asarray(scope.get(pname))
        if transpose:
            arr = arr.T
        elif transpose is None and arr.ndim == 2 \
                and arr.shape[0] == arr.shape[1] \
                and arr.shape == cur.shape and transpose_linear:
            raise ValueError(
                f"{tkey!r} -> {pname!r}: square 2-D weight "
                f"{arr.shape} is transpose-ambiguous; map it as "
                f"({pname!r}, True) for a torch Linear weight or "
                f"({pname!r}, False) to import as-is")
        if arr.shape != cur.shape:
            if (transpose is None and transpose_linear and arr.ndim == 2
                    and arr.T.shape == cur.shape):
                arr = arr.T
            else:
                raise ValueError(
                    f"{tkey!r} -> {pname!r}: shape {arr.shape} does not "
                    f"match parameter {cur.shape}"
                    + (" (even transposed)" if arr.ndim == 2 else ""))
        scope.set(pname, arr.astype(cur.dtype))
        done.append(pname)
    return done
