"""paddle.v2 API compatibility namespace.

Reference: python/paddle/v2/__init__.py — the surface the v1_api_demo scripts
and cluster tutorials drive: ``paddle.init``, ``paddle.layer.*`` (DSL),
``paddle.activation.*``, ``paddle.optimizer.*``, ``paddle.trainer.SGD``,
``paddle.dataset``, ``paddle.reader``, ``paddle.batch``, ``paddle.infer``,
``paddle.parameters``.

Usage (a v1_api_demo/mnist/api_train.py-shaped script)::

    import paddle_tpu.v2 as paddle
    paddle.init(use_gpu=False, trainer_count=1)
    images = paddle.layer.data(name='pixel', size=784)
    label = paddle.layer.data(name='label', size=10)
    h = paddle.layer.fc(input=images, size=128,
                        act=paddle.activation.Relu())
    out = paddle.layer.fc(input=h, size=10,
                          act=paddle.activation.Softmax())
    cost = paddle.layer.classification_cost(input=out, label=label)
    trainer = paddle.trainer.SGD(
        cost=cost, update_equation=paddle.optimizer.Momentum(0.9,
                                                             learning_rate=0.1))
    trainer.train(paddle.batch(paddle.dataset.mnist.train(), 128),
                  num_passes=2, event_handler=...)
"""
from __future__ import annotations

import types as _types

from . import dataset, image, reader  # noqa: F401
from . import trainer as _trainer_mod
from . import optimizer as _opt
from .reader import batch  # noqa: F401
from .trainer import events, infer  # noqa: F401
from .data_feeder import DataFeeder  # noqa: F401
from . import trainer_config_helpers as _dsl


def init(use_gpu=None, use_tpu=None, trainer_count=1, **kw):
    """paddle.init — device selection is owned by JAX/XLA; flags recorded."""
    from . import flags
    if trainer_count:
        flags.set_flag("trainer_count", trainer_count)
    return None


# -- paddle.data_type (v2/data_type.py: InputType descriptors) ---------------
class InputType:
    """v2 InputType: dim + sequence level + value kind."""

    def __init__(self, dim, seq_type, type):  # noqa: A002 (reference name)
        self.dim = dim
        self.seq_type = seq_type
        self.type = type


def _dt(kind, seq):
    def f(dim=None, *a, **kw):
        return InputType(dim, seq, kind)
    return f


data_type = _types.SimpleNamespace(
    dense_vector=_dt("dense", 0),
    dense_array=_dt("dense", 0),
    dense_vector_sequence=_dt("dense", 1),
    integer_value=_dt("int", 0),
    integer_value_sequence=_dt("int", 1),
    integer_value_sub_sequence=_dt("int", 2),
    sparse_binary_vector=_dt("sparse_binary", 0),
    sparse_binary_vector_sequence=_dt("sparse_binary", 1),
    sparse_float_vector=_dt("sparse_float", 0),
    sparse_float_vector_sequence=_dt("sparse_float", 1),
    InputType=InputType,
)


def _v2_data(name, type=None, size=None, **kw):  # noqa: A002
    """v2 layer.data(name=, type=paddle.data_type.X(dim)): creates the v1
    data layer and eagerly applies the InputType's dtype/sequence level
    (the v1 path retypes lazily at first integer use).  The v1-style
    positional ``data(name, size)`` form still works."""
    import numpy as _np
    if type is not None and not isinstance(type, InputType):
        if isinstance(type, int) and size is None:
            type, size = None, type      # v1 positional data(name, size)
        else:
            raise TypeError(
                f"layer.data 'type' must be a paddle.data_type InputType "
                f"(got {type!r}); for the v1 form use data(name, size=N)")
    if type is not None:
        size = type.dim if type.dim is not None else size
    v = _dsl.data_layer(name, size, **kw)
    if type is None:
        return v
    if type.type == "int":
        v.dtype = _np.dtype("int64")
        if type.seq_type:
            v.lod_level = type.seq_type
            v.shape = (-1, -1)
        else:
            v.shape = (-1, 1)
    elif type.type == "dense":
        if type.seq_type:                # dense sequence: [B, T, dim]
            v.lod_level = type.seq_type
            v.shape = (-1, -1, type.dim)
    else:
        raise NotImplementedError(
            f"sparse input type {type.type!r} is not supported: feed "
            f"dense rows (dense_vector) or integer id lists "
            f"(integer_value_sequence) instead — SelectedRows-style "
            f"sparsity lives in the embedding tables, not the feeds")
    return v


# -- paddle.layer / paddle.networks ------------------------------------------
# The v2 layer module auto-generates its surface from trainer_config_helpers
# (v2/layer.py: every *_layer becomes the suffix-stripped name).  The DSL now
# exports the full 133-function surface, so build the namespaces from it.
_layer_ns = {}
for _n in _dsl.__all__:
    _obj = getattr(_dsl, _n, None)
    if _obj is None:
        continue
    _layer_ns.setdefault(_n, _obj)
    if _n.endswith("_layer"):
        _layer_ns[_n[:-len("_layer")]] = _obj
def _parse_network(*output_layers, extra_layers=None):
    """v2 layer.parse_network (v2/layer.py:263): the model config for the
    given outputs — here the pruned Program slice (the ModelConfig proto's
    role; serialize with .to_dict())."""
    outs = []
    for o in output_layers:
        outs.extend(o if isinstance(o, (list, tuple)) else [o])
    outs.extend(extra_layers or [])
    return outs[0].block.program.prune(outs)


_layer_ns.update(
    data=_v2_data,
    square_error_cost=_dsl.regression_cost,
    regression_cost=_dsl.regression_cost,
    max_id=_dsl.maxid_layer,
    parse_network=_parse_network,
)
layer = _types.SimpleNamespace(**_layer_ns)

_net_names = (
    "simple_lstm", "simple_gru", "simple_gru2", "bidirectional_lstm",
    "bidirectional_gru", "sequence_conv_pool", "simple_attention",
    "dot_product_attention", "multi_head_attention", "img_conv_group",
    "simple_img_conv_pool", "img_conv_bn_pool", "img_separable_conv",
    "vgg_16_network", "small_vgg", "lstmemory_unit", "lstmemory_group",
    "gru_unit", "gru_group", "text_conv_pool",
)
networks = _types.SimpleNamespace(
    **{n: getattr(_dsl, n) for n in _net_names if hasattr(_dsl, n)})

# -- paddle.activation / paddle.pooling / paddle.attr ------------------------
activation = _types.SimpleNamespace(
    **{n[:-len("Activation")]: getattr(_dsl, n) for n in _dsl.__all__
       if n.endswith("Activation")})
pooling = _types.SimpleNamespace(
    **{n[:-len("Pooling")]: getattr(_dsl, n) for n in _dsl.__all__
       if n.endswith("Pooling")})
attr = _types.SimpleNamespace(
    Param=_dsl.ParamAttr, ParamAttr=_dsl.ParamAttr,
    Extra=_dsl.ExtraAttr, ExtraAttr=_dsl.ExtraAttr,
    ParameterAttribute=_dsl.ParamAttr,
    ExtraLayerAttribute=_dsl.ExtraLayerAttribute)

# -- paddle.evaluator (v2 evaluator namespace: *_evaluator stripped) ---------
evaluator = _types.SimpleNamespace(
    **{n[:-len("_evaluator")]: getattr(_dsl, n) for n in _dsl.__all__
       if n.endswith("_evaluator")})

# -- paddle.op (v2/op.py: unary math over layers; the +-*/ overloads live
# on core Variable so every front end gets them) -----------------------------
from .trainer_config_helpers import layer_math as _lm  # noqa: E402

op = _types.SimpleNamespace(
    **{n: getattr(_lm, n) for n in _lm.__all__})


# -- paddle.inference (v2/inference.py Inference class) ----------------------
class Inference:
    """v2 Inference: bind an output layer once, infer repeatedly
    (inference.py:10; parameters are the live scope here).  ``field``
    keeps the reference semantics: 'value' returns the raw outputs, 'id'
    the argmax ids."""

    def __init__(self, output_layer, parameters=None):
        self._out = output_layer

    def infer(self, input, feeding=None, field="value", *,  # noqa: A002
              feed_list=None, **kw):
        import numpy as _np
        res = infer(output_layer=self._out, input=input,
                    feed_list=feed_list, feeding=feeding, **kw)
        if field == "value":
            return res
        if field == "id":
            return _np.argmax(_np.asarray(res), axis=-1)
        raise ValueError(f"field must be 'value' or 'id', got {field!r}")


inference = _types.SimpleNamespace(Inference=Inference, infer=infer)


# -- paddle.optimizer (v2 signature: momentum first, lr kwarg) ---------------
class _V2Opt:
    def _make(self):
        raise NotImplementedError


class Momentum(_V2Opt):
    def __init__(self, momentum=0.9, learning_rate=1e-3, regularization=None,
                 **kw):
        self._o = _opt.Momentum(learning_rate=learning_rate,
                                momentum=momentum,
                                regularization=_reg(regularization))

    def _make(self):
        return self._o


class Adam(_V2Opt):
    def __init__(self, learning_rate=1e-3, beta1=0.9, beta2=0.999,
                 regularization=None, **kw):
        self._o = _opt.Adam(learning_rate=learning_rate, beta1=beta1,
                            beta2=beta2,
                            regularization=_reg(regularization))

    def _make(self):
        return self._o


class AdaGrad(_V2Opt):
    def __init__(self, learning_rate=1e-3, regularization=None, **kw):
        self._o = _opt.Adagrad(learning_rate=learning_rate,
                               regularization=_reg(regularization))

    def _make(self):
        return self._o


class RMSProp(_V2Opt):
    def __init__(self, learning_rate=1e-3, regularization=None, **kw):
        self._o = _opt.RMSProp(learning_rate=learning_rate,
                               regularization=_reg(regularization))

    def _make(self):
        return self._o


def _reg(r):
    if r is None:
        return None
    if hasattr(r, "make"):
        return r.make()
    return r


optimizer = _types.SimpleNamespace(Momentum=Momentum, Adam=Adam,
                                   AdaGrad=AdaGrad, RMSProp=RMSProp)


# -- paddle.parameters (the v2 Parameters facade over the scope) -------------
class Parameters:
    """v2 parameters.create analog: a view over the global scope."""

    @staticmethod
    def create(*cost):
        return Parameters()

    def keys(self):
        from .core.scope import global_scope
        return global_scope().keys()

    def get(self, name):
        import numpy as np
        from .core.scope import global_scope
        return np.asarray(global_scope().get(name))

    def set(self, name, value):
        import jax.numpy as jnp
        from .core.scope import global_scope
        global_scope().set(name, jnp.asarray(value))


parameters = _types.SimpleNamespace(create=Parameters.create,
                                    Parameters=Parameters)


# -- paddle.trainer ----------------------------------------------------------
class _SGDShim(_trainer_mod.SGD):
    """v2 SGD(cost, parameters=None, update_equation=v2-optimizer)."""

    def __init__(self, cost=None, parameters=None, update_equation=None,
                 extra_layers=None, is_local=True, **kw):
        ue = update_equation._make() if isinstance(update_equation, _V2Opt) \
            else update_equation
        super().__init__(cost, parameters=parameters, update_equation=ue,
                         extra_layers=extra_layers or (), is_local=is_local)


trainer = _types.SimpleNamespace(SGD=_SGDShim)
event = events


# -- paddle.v2.master (Go master client analog) ------------------------------
class _MasterClientShim:
    """v2 master.client(addr_or_etcd, buf_size): consume dataset task chunks
    from the (TCP) task-queue master — reference python/paddle/v2/master/
    client.py over the Go service; here over distributed.master's JSON-RPC
    server."""

    def __init__(self, addr, buf_size=100, etcd_endpoints=None, **kw):
        from .distributed.master import MasterClient
        self._c = MasterClient(addr)
        self.buf_size = buf_size

    def set_dataset(self, paths):
        self._c.set_dataset(list(paths))

    def next_record(self):
        """Iterate records across master-handed chunks (a chunk is any
        iterable of records; file paths are read line-wise).  An empty todo
        queue with tasks still PENDING on other trainers is not the end:
        a crashed peer's lease may lapse and requeue its task here."""
        import time as _time
        while True:
            t = self._c.get_task()
            if t is None:
                st = self._c.stats()
                if st["pending"] > 0:
                    _time.sleep(0.2)   # a peer's lease may still lapse
                    continue
                return
            try:
                for chunk in t.chunks:
                    if isinstance(chunk, str):
                        with open(chunk, "rb") as f:
                            yield from f
                    elif isinstance(chunk, (list, tuple)):
                        yield from chunk
                    else:
                        yield chunk
            except Exception:
                self._c.task_failed(t.task_id)
                continue
            self._c.task_finished(t.task_id)

    def reader(self):
        def _r():
            yield from self.next_record()
        return _r

    def close(self):
        self._c.close()


master = _types.SimpleNamespace(client=_MasterClientShim)


# -- paddle.v2.topology ------------------------------------------------------
class Topology:
    """v2 Topology(cost) facade: the serializable network description
    (reference python/paddle/v2/topology.py wraps the TrainerConfig proto;
    here the Program IR serializes as JSON)."""

    def __init__(self, layers_or_cost, extra_layers=None):
        from .core.program import default_main_program, default_startup_program
        outs = layers_or_cost if isinstance(layers_or_cost, (list, tuple)) \
            else [layers_or_cost]
        self.outputs = list(outs)
        self.main_program = outs[0].block.program if hasattr(
            outs[0], "block") else default_main_program()
        self.startup_program = default_startup_program()

    def serialize(self):
        import json as _json
        return _json.dumps(self.main_program.to_dict())

    def data_layers(self):
        return {v.name: v for b in self.main_program.blocks
                for v in b.vars.values() if getattr(v, "is_data", False)}

    def get_layer(self, name):
        for b in self.main_program.blocks:
            if name in b.vars:
                return b.vars[name]
        return None


topology = _types.SimpleNamespace(Topology=Topology)


# -- paddle.v2.plot ----------------------------------------------------------
class Ploter:
    """v2 plot.Ploter (python/paddle/v2/plot/plot.py): accumulate named
    curves during training and render/save them (Agg backend, so it works
    headless like the reference's notebook fallback)."""

    def __init__(self, *titles):
        self.titles = list(titles)
        self.data = {t: ([], []) for t in titles}

    def append(self, title, step, value):
        xs, ys = self.data[title]
        xs.append(step)
        ys.append(float(value))

    def plot(self, path=None):
        import matplotlib
        matplotlib.use("Agg")
        import matplotlib.pyplot as plt
        fig, ax = plt.subplots()
        for t in self.titles:
            xs, ys = self.data[t]
            ax.plot(xs, ys, label=t)
        ax.legend()
        ax.set_xlabel("step")
        if path:
            fig.savefig(path)
        plt.close(fig)
        return fig

    def reset(self):
        for t in self.titles:
            self.data[t] = ([], [])


plot = _types.SimpleNamespace(Ploter=Ploter)
