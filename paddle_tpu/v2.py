"""paddle.v2 API compatibility namespace.

Reference: python/paddle/v2/__init__.py — the surface the v1_api_demo scripts
and cluster tutorials drive: ``paddle.init``, ``paddle.layer.*`` (DSL),
``paddle.activation.*``, ``paddle.optimizer.*``, ``paddle.trainer.SGD``,
``paddle.dataset``, ``paddle.reader``, ``paddle.batch``, ``paddle.infer``,
``paddle.parameters``.

Usage (a v1_api_demo/mnist/api_train.py-shaped script)::

    import paddle_tpu.v2 as paddle
    paddle.init(use_gpu=False, trainer_count=1)
    images = paddle.layer.data(name='pixel', size=784)
    label = paddle.layer.data(name='label', size=10)
    h = paddle.layer.fc(input=images, size=128,
                        act=paddle.activation.Relu())
    out = paddle.layer.fc(input=h, size=10,
                          act=paddle.activation.Softmax())
    cost = paddle.layer.classification_cost(input=out, label=label)
    trainer = paddle.trainer.SGD(
        cost=cost, update_equation=paddle.optimizer.Momentum(0.9,
                                                             learning_rate=0.1))
    trainer.train(paddle.batch(paddle.dataset.mnist.train(), 128),
                  num_passes=2, event_handler=...)
"""
from __future__ import annotations

import types as _types

from . import dataset, image, reader  # noqa: F401
from . import trainer as _trainer_mod
from . import optimizer as _opt
from .reader import batch  # noqa: F401
from .trainer import events, infer  # noqa: F401
from . import trainer_config_helpers as _dsl


def init(use_gpu=None, use_tpu=None, trainer_count=1, **kw):
    """paddle.init — device selection is owned by JAX/XLA; flags recorded."""
    from . import flags
    if trainer_count:
        flags.set_flag("trainer_count", trainer_count)
    return None


# -- paddle.layer ------------------------------------------------------------
layer = _types.SimpleNamespace(
    data=_dsl.data_layer,
    fc=_dsl.fc_layer,
    img_conv=_dsl.img_conv_layer,
    img_pool=_dsl.img_pool_layer,
    img_cmrnorm=_dsl.img_cmrnorm_layer,
    batch_norm=_dsl.batch_norm_layer,
    dropout=_dsl.dropout_layer,
    embedding=_dsl.embedding_layer,
    concat=_dsl.concat_layer,
    addto=_dsl.addto_layer,
    lstmemory=_dsl.lstmemory,
    simple_lstm=_dsl.simple_lstm,
    last_seq=_dsl.last_seq,
    first_seq=_dsl.first_seq,
    classification_cost=_dsl.classification_cost,
    cross_entropy_cost=_dsl.cross_entropy_cost,
    square_error_cost=_dsl.regression_cost,
    regression_cost=_dsl.regression_cost,
    # sequence / generation DSL surface (round-3 additions)
    recurrent_group=_dsl.recurrent_group,
    memory=_dsl.memory,
    mixed=_dsl.mixed_layer,
    full_matrix_projection=_dsl.full_matrix_projection,
    table_projection=_dsl.table_projection,
    identity_projection=_dsl.identity_projection,
    dotmul_projection=_dsl.dotmul_projection,
    trans_full_matrix_projection=_dsl.trans_full_matrix_projection,
    recurrent=_dsl.recurrent_layer,
    lstmemory_group=_dsl.lstmemory_group,
    grumemory=_dsl.grumemory,
    gru_group=_dsl.gru_group,
    simple_gru=_dsl.simple_gru,
    beam_search=_dsl.beam_search,
    crf=_dsl.crf_layer,
    crf_decoding=_dsl.crf_decoding_layer,
    max_id=_dsl.maxid_layer,
    pooling=_dsl.pooling_layer,
    expand=_dsl.expand_layer,
    scaling=_dsl.scaling_layer,
    StaticInput=_dsl.StaticInput,
    GeneratedInput=_dsl.GeneratedInput,
    SubsequenceInput=_dsl.SubsequenceInput,
)

# paddle.networks (v2 networks namespace: the composite helpers)
networks = _types.SimpleNamespace(
    simple_lstm=_dsl.simple_lstm,
    simple_gru=_dsl.simple_gru,
    bidirectional_lstm=_dsl.bidirectional_lstm,
    sequence_conv_pool=_dsl.sequence_conv_pool,
    simple_attention=_dsl.simple_attention,
    img_conv_group=_dsl.img_conv_group,
)

# -- paddle.activation / paddle.pooling --------------------------------------
activation = _types.SimpleNamespace(
    Linear=_dsl.LinearActivation, Relu=_dsl.ReluActivation,
    Sigmoid=_dsl.SigmoidActivation, Tanh=_dsl.TanhActivation,
    Softmax=_dsl.SoftmaxActivation, Identity=_dsl.IdentityActivation,
)
pooling = _types.SimpleNamespace(
    Max=_dsl.MaxPooling, Avg=_dsl.AvgPooling, Sum=_dsl.SumPooling,
)


# -- paddle.optimizer (v2 signature: momentum first, lr kwarg) ---------------
class _V2Opt:
    def _make(self):
        raise NotImplementedError


class Momentum(_V2Opt):
    def __init__(self, momentum=0.9, learning_rate=1e-3, regularization=None,
                 **kw):
        self._o = _opt.Momentum(learning_rate=learning_rate,
                                momentum=momentum,
                                regularization=_reg(regularization))

    def _make(self):
        return self._o


class Adam(_V2Opt):
    def __init__(self, learning_rate=1e-3, beta1=0.9, beta2=0.999,
                 regularization=None, **kw):
        self._o = _opt.Adam(learning_rate=learning_rate, beta1=beta1,
                            beta2=beta2,
                            regularization=_reg(regularization))

    def _make(self):
        return self._o


class AdaGrad(_V2Opt):
    def __init__(self, learning_rate=1e-3, regularization=None, **kw):
        self._o = _opt.Adagrad(learning_rate=learning_rate,
                               regularization=_reg(regularization))

    def _make(self):
        return self._o


class RMSProp(_V2Opt):
    def __init__(self, learning_rate=1e-3, regularization=None, **kw):
        self._o = _opt.RMSProp(learning_rate=learning_rate,
                               regularization=_reg(regularization))

    def _make(self):
        return self._o


def _reg(r):
    if r is None:
        return None
    if hasattr(r, "make"):
        return r.make()
    return r


optimizer = _types.SimpleNamespace(Momentum=Momentum, Adam=Adam,
                                   AdaGrad=AdaGrad, RMSProp=RMSProp)


# -- paddle.parameters (the v2 Parameters facade over the scope) -------------
class Parameters:
    """v2 parameters.create analog: a view over the global scope."""

    @staticmethod
    def create(*cost):
        return Parameters()

    def keys(self):
        from .core.scope import global_scope
        return global_scope().keys()

    def get(self, name):
        import numpy as np
        from .core.scope import global_scope
        return np.asarray(global_scope().get(name))

    def set(self, name, value):
        import jax.numpy as jnp
        from .core.scope import global_scope
        global_scope().set(name, jnp.asarray(value))


parameters = _types.SimpleNamespace(create=Parameters.create,
                                    Parameters=Parameters)


# -- paddle.trainer ----------------------------------------------------------
class _SGDShim(_trainer_mod.SGD):
    """v2 SGD(cost, parameters=None, update_equation=v2-optimizer)."""

    def __init__(self, cost=None, parameters=None, update_equation=None,
                 extra_layers=None, is_local=True, **kw):
        ue = update_equation._make() if isinstance(update_equation, _V2Opt) \
            else update_equation
        super().__init__(cost, parameters=parameters, update_equation=ue,
                         extra_layers=extra_layers or (), is_local=is_local)


trainer = _types.SimpleNamespace(SGD=_SGDShim)
event = events


# -- paddle.v2.master (Go master client analog) ------------------------------
class _MasterClientShim:
    """v2 master.client(addr_or_etcd, buf_size): consume dataset task chunks
    from the (TCP) task-queue master — reference python/paddle/v2/master/
    client.py over the Go service; here over distributed.master's JSON-RPC
    server."""

    def __init__(self, addr, buf_size=100, etcd_endpoints=None, **kw):
        from .distributed.master import MasterClient
        self._c = MasterClient(addr)
        self.buf_size = buf_size

    def set_dataset(self, paths):
        self._c.set_dataset(list(paths))

    def next_record(self):
        """Iterate records across master-handed chunks (a chunk is any
        iterable of records; file paths are read line-wise).  An empty todo
        queue with tasks still PENDING on other trainers is not the end:
        a crashed peer's lease may lapse and requeue its task here."""
        import time as _time
        while True:
            t = self._c.get_task()
            if t is None:
                st = self._c.stats()
                if st["pending"] > 0:
                    _time.sleep(0.2)   # a peer's lease may still lapse
                    continue
                return
            try:
                for chunk in t.chunks:
                    if isinstance(chunk, str):
                        with open(chunk, "rb") as f:
                            yield from f
                    elif isinstance(chunk, (list, tuple)):
                        yield from chunk
                    else:
                        yield chunk
            except Exception:
                self._c.task_failed(t.task_id)
                continue
            self._c.task_finished(t.task_id)

    def reader(self):
        def _r():
            yield from self.next_record()
        return _r

    def close(self):
        self._c.close()


master = _types.SimpleNamespace(client=_MasterClientShim)


# -- paddle.v2.topology ------------------------------------------------------
class Topology:
    """v2 Topology(cost) facade: the serializable network description
    (reference python/paddle/v2/topology.py wraps the TrainerConfig proto;
    here the Program IR serializes as JSON)."""

    def __init__(self, layers_or_cost, extra_layers=None):
        from .core.program import default_main_program, default_startup_program
        outs = layers_or_cost if isinstance(layers_or_cost, (list, tuple)) \
            else [layers_or_cost]
        self.outputs = list(outs)
        self.main_program = outs[0].block.program if hasattr(
            outs[0], "block") else default_main_program()
        self.startup_program = default_startup_program()

    def serialize(self):
        import json as _json
        return _json.dumps(self.main_program.to_dict())

    def data_layers(self):
        return {v.name: v for b in self.main_program.blocks
                for v in b.vars.values() if getattr(v, "is_data", False)}

    def get_layer(self, name):
        for b in self.main_program.blocks:
            if name in b.vars:
                return b.vars[name]
        return None


topology = _types.SimpleNamespace(Topology=Topology)


# -- paddle.v2.plot ----------------------------------------------------------
class Ploter:
    """v2 plot.Ploter (python/paddle/v2/plot/plot.py): accumulate named
    curves during training and render/save them (Agg backend, so it works
    headless like the reference's notebook fallback)."""

    def __init__(self, *titles):
        self.titles = list(titles)
        self.data = {t: ([], []) for t in titles}

    def append(self, title, step, value):
        xs, ys = self.data[title]
        xs.append(step)
        ys.append(float(value))

    def plot(self, path=None):
        import matplotlib
        matplotlib.use("Agg")
        import matplotlib.pyplot as plt
        fig, ax = plt.subplots()
        for t in self.titles:
            xs, ys = self.data[t]
            ax.plot(xs, ys, label=t)
        ax.legend()
        ax.set_xlabel("step")
        if path:
            fig.savefig(path)
        plt.close(fig)
        return fig

    def reset(self):
        for t in self.titles:
            self.data[t] = ([], [])


plot = _types.SimpleNamespace(Ploter=Ploter)
