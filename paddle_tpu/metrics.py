"""Host-side streaming metrics (the v1 gserver/evaluators capability —
classification error, precision/recall, AUC — as numpy accumulators for use
outside the program graph)."""
from __future__ import annotations

import numpy as np


class Metric:
    def reset(self):
        raise NotImplementedError

    def update(self, *args):
        raise NotImplementedError

    def eval(self):
        raise NotImplementedError


class Accuracy(Metric):
    def __init__(self):
        self.reset()

    def reset(self):
        self.correct = 0.0
        self.total = 0.0

    def update(self, preds, labels):
        preds = np.asarray(preds)
        labels = np.asarray(labels).reshape(-1)
        if preds.ndim > 1 and preds.shape[-1] > 1:
            preds = preds.argmax(-1)
        preds = preds.reshape(-1)
        self.correct += float((preds == labels).sum())
        self.total += labels.size

    def eval(self):
        return self.correct / max(self.total, 1.0)


class Auc(Metric):
    def __init__(self, num_thresholds=200):
        self.n = num_thresholds
        self.reset()

    def reset(self):
        self.pos = np.zeros(self.n + 1)
        self.neg = np.zeros(self.n + 1)

    def update(self, probs, labels):
        probs = np.asarray(probs)
        labels = np.asarray(labels).reshape(-1)
        if probs.ndim == 2 and probs.shape[1] == 2:
            probs = probs[:, 1]
        probs = probs.reshape(-1)
        idx = np.clip((probs * self.n).astype(int), 0, self.n)
        np.add.at(self.pos, idx, labels > 0)
        np.add.at(self.neg, idx, labels <= 0)

    def eval(self):
        tp = np.cumsum(self.pos[::-1])[::-1]
        fp = np.cumsum(self.neg[::-1])[::-1]
        tpr = tp / max(tp[0], 1.0)
        fpr = fp / max(fp[0], 1.0)
        return float(-np.trapezoid(tpr, fpr))


class PrecisionRecall(Metric):
    def __init__(self, num_classes):
        self.num_classes = num_classes
        self.reset()

    def reset(self):
        self.tp = np.zeros(self.num_classes)
        self.fp = np.zeros(self.num_classes)
        self.fn = np.zeros(self.num_classes)

    def update(self, preds, labels):
        preds = np.asarray(preds)
        if preds.ndim > 1 and preds.shape[-1] > 1:
            preds = preds.argmax(-1)
        preds = preds.reshape(-1)
        labels = np.asarray(labels).reshape(-1)
        for c in range(self.num_classes):
            self.tp[c] += float(((preds == c) & (labels == c)).sum())
            self.fp[c] += float(((preds == c) & (labels != c)).sum())
            self.fn[c] += float(((preds != c) & (labels == c)).sum())

    def eval(self):
        prec = self.tp / np.maximum(self.tp + self.fp, 1.0)
        rec = self.tp / np.maximum(self.tp + self.fn, 1.0)
        f1 = 2 * prec * rec / np.maximum(prec + rec, 1e-6)
        return prec.mean(), rec.mean(), f1.mean()


class EditDistance(Metric):
    def __init__(self):
        self.reset()

    def reset(self):
        self.total_dist = 0.0
        self.count = 0

    def update(self, dists):
        d = np.asarray(dists).reshape(-1)
        self.total_dist += float(d.sum())
        self.count += d.size

    def eval(self):
        return self.total_dist / max(self.count, 1)
