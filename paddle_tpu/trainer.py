"""High-level trainer with the v2 event-loop surface.

Reference: python/paddle/v2/trainer.py (SGD :124 train loop, event_handler
protocol python/paddle/v2/event.py) — the API the reference's demos and
benchmarks drive (v1_api_demo/mnist/api_train.py).  Internally this builds
the fluid-style program (optimizer.minimize + Executor) — the two reference
generations collapse into one path here.
"""
from __future__ import annotations

import os
import signal as _signal
from typing import Callable, List, Optional, Sequence

import numpy as np

from . import observability
from . import optimizer as optimizer_mod
from .core.executor import Executor
from .core.program import (Program, Variable, default_main_program,
                           default_startup_program)
from .core.scope import global_scope
from .data_feeder import DataFeeder
from .testing import faultinject as _fi


class events:
    """Event types passed to event_handler (python/paddle/v2/event.py)."""

    class BeginPass:
        def __init__(self, pass_id):
            self.pass_id = pass_id

    class EndPass:
        def __init__(self, pass_id, evaluator=None):
            self.pass_id = pass_id
            self.evaluator = evaluator

    class BeginIteration:
        def __init__(self, pass_id, batch_id):
            self.pass_id = pass_id
            self.batch_id = batch_id

    class EndIteration:
        def __init__(self, pass_id, batch_id, cost, metrics):
            self.pass_id = pass_id
            self.batch_id = batch_id
            self.cost = cost
            self.metrics = metrics


class SGD:
    """v2-style trainer: SGD(cost, parameters=None, update_equation=opt).

    ``update_equation`` is any paddle_tpu.optimizer.Optimizer (the v2 API
    took a v2 optimizer; same role).  ``extra_layers`` are fetched alongside
    cost every iteration and reported in EndIteration.metrics.
    """

    def __init__(self, cost: Variable, parameters=None,
                 update_equation=None, extra_layers: Sequence = (),
                 is_local=True, place=None):
        self.cost = cost
        self.extra = list(extra_layers or ())
        self.optimizer = update_equation or optimizer_mod.SGD(
            learning_rate=0.01)
        self.main_program = cost.block.program
        self.optimizer.minimize(cost)
        self.exe = Executor(place)
        self._initialized = False

    # -- training ----------------------------------------------------------
    def train(self, reader: Callable, num_passes: int = 1,
              event_handler: Optional[Callable] = None,
              feeding=None, feed_list: Optional[Sequence[Variable]] = None,
              steps_per_dispatch: int = 1, pipeline=False,
              warmup: bool = False, validate: Optional[bool] = None,
              autotune: Optional[bool] = None,
              auto_shard=None,
              checkpoint_dir: Optional[str] = None, resume: bool = False,
              save_every_n_steps: Optional[int] = None, master=None,
              handle_signals: bool = True, elastic=None,
              sparse_tables=None):
        """reader yields batches (lists of rows); feeding maps data-layer
        names to row positions (v2 trainer.py feeding) or pass feed_list.

        ``steps_per_dispatch > 1`` stacks runs of consecutive same-shape
        batches and executes each run as ONE device-side scan
        (`Executor.run_steps` with stacked feeds) — the compiled training
        loop.  Iteration events still fire per batch (after the dispatch
        that contained them); differently-shaped batches (bucketed
        padding) fall back to per-batch dispatch automatically.

        ``pipeline`` turns on the asynchronous input pipeline
        (``Executor.run_pipelined``): batch decode, padding and
        ``device_put`` staging move onto worker threads overlapped with
        device compute, and same-shape runs dispatch as compiled K-step
        scans.  Pass ``True`` for defaults or a dict with any of
        ``steps_per_dispatch`` (default 8, or the ``steps_per_dispatch``
        argument when > 1), ``num_workers`` (reader prefetch workers,
        default 1; 0 folds decode into the staging thread, right when
        host cores are scarce; more than 1 reorders batches), ``buffer_size``
        (decoded-batch queue bound, default 4) and ``prefetch_depth``
        (staged dispatches in flight, default 2).  Step math is identical
        to the per-batch loop; only event timing changes (events for a
        dispatch fire after it completes).

        ``warmup=True`` pays trace/lower/compile BEFORE the training loop
        starts: one batch is peeked from ``reader`` (for its shapes only)
        and the step variant(s) this loop will dispatch are compiled ahead
        of time (``Executor.compile``), so the first real batch executes a
        ready executable.  With a persistent cache directory set
        (``PADDLE_TPU_CACHE_DIR``), warmup in a deploy step also persists
        the executables for later processes.  Bucketed readers whose later
        batches change shape still compile those variants on first use.

        ``validate=True`` runs the static program verifier
        (``paddle_tpu.analysis``) over the startup and training programs
        before their first trace: a malformed graph fails with a stable
        ``PT0xx`` diagnostic naming the op instead of a JAX trace error.
        ``False`` forces it off; ``None`` (default) defers to the
        ``validate`` flag (``PADDLE_TPU_VALIDATE=1``).  The override
        applies to this call only — the executor's own setting is
        restored afterwards.

        ``autotune=True`` replays persisted autotuner winners
        (``paddle_tpu.tuning``) into this loop's omitted knobs: the
        pipelined path's ``steps_per_dispatch``/``prefetch_depth`` and
        reader ``num_workers``/``buffer_size`` (any knob given
        explicitly — argument or pipeline dict — always wins), plus the
        executor's device-side tuned compiler options.  ``False`` forces
        it off; ``None`` (default) defers to the executor /
        ``autotune`` flag (``PADDLE_TPU_AUTOTUNE=1``).  Replay never
        searches — with no persisted record every knob keeps its
        hand-picked default.  Search with ``python -m paddle_tpu tune
        <target>``.  Like ``validate``, the override applies to this
        call only.

        ``auto_shard`` turns on the static auto-sharding planner
        (``paddle_tpu.analysis.planner``): when the executor's
        ``param_specs``/``feed_specs`` are omitted, a plan proposed for
        its mesh (validated by the PT030/PT031 lints) fills them before
        the first trace.  ``True`` requires the trainer's executor to
        already be a ``ShardedExecutor``; a ``{'dp': 8}`` dict or a
        ``"dp=8,tp=2"`` string additionally builds the mesh over the
        local devices and swaps the trainer onto a
        ``ShardedExecutor(auto_shard=True)`` (only before the first
        ``train()`` call — the swap must precede parameter init).

        ``checkpoint_dir`` turns on the fault-tolerant runtime
        (``paddle_tpu.train_state``): every ``save_every_n_steps``
        completed batches a checkpoint of the full scope PLUS the loop's
        :class:`~paddle_tpu.train_state.TrainState` (step/pass/batch
        counters — the RNG derivation state) commits atomically, and a
        SIGTERM/SIGINT finishes the in-flight dispatch, commits an
        emergency checkpoint and exits
        :data:`~paddle_tpu.faults.EXIT_PREEMPTED` (raise:
        :class:`~paddle_tpu.faults.Preempted`) so a supervisor
        (``distributed.supervisor``) relaunches it.  ``resume=True``
        restores the newest intact checkpoint and continues — with a
        deterministic, restartable ``reader`` and an order-preserving
        pipeline config (``num_workers <= 1``) the resumed run's fetches
        are BIT-IDENTICAL to an uninterrupted one (the chaos suite pins
        this with subprocess kills); an empty directory starts fresh, so
        a supervised command can always pass ``resume=True``.  Saves
        happen only at dispatch boundaries (scope consistency); with
        chunked dispatch the effective cadence rounds up to the chunk.
        ``master``: an in-process ``distributed.Master`` whose task-queue
        snapshot should commit alongside each checkpoint (and be restored
        on resume).  ``handle_signals=False`` skips installing handlers
        (e.g. when embedding the trainer in a host that owns them).

        ``elastic``: a duck-typed elastic-worker hook (normally a
        ``distributed.elastic.ElasticWorker`` — the trainer itself never
        imports the elastic module, so the zero-cost-when-unused
        contract holds statically).  The hook's ``state()`` rides in
        every checkpoint's ``TrainState.elastic``; ``bind(ckpt, ts)``
        runs after restore (registering with the membership layer and
        rewinding the master-sharded stream — which is WHY the
        batch-skip resume fast-forward is forced to zero here: a
        master-backed stream resumes by task re-serve + within-task
        offset, not by replaying the reader from the top);
        ``after_batch()`` runs per completed batch (heartbeat, drain
        command, injection sites, post-commit ``task_finished``);
        ``on_complete()`` runs after the final save.  Requires
        ``checkpoint_dir`` and the per-batch dispatch path
        (``steps_per_dispatch == 1``, no ``pipeline``) — the elastic
        commit protocol needs every batch to be a dispatch boundary.

        ``sparse_tables``: a duck-typed host sparse-table session
        (normally a :class:`paddle_tpu.sparse.SparseSession` — the
        trainer itself never imports the sparse package, so the
        zero-cost-when-unused contract holds statically).  Per batch the
        loop calls ``prepare_feed`` (id dedup → host pull → rows/inverse
        feed injection) before the dispatch and ``complete`` with the
        fetched ``<rows>@GRAD`` arrays after it (the host-side sparse
        optimizer push).  The per-batch path is fully synchronous by
        default — pull → step → push, the semantics the dense-parity
        test pins bit-identical; the chunked (``steps_per_dispatch >
        1``) and ``pipeline`` paths pull up to a dispatch-chunk (plus
        prefetch depth) ahead of the pushes — bounded-staleness ASYNC
        updates, the reference's async-pserver SGD semantics.  A
        session with ``prefetch_depth > 0`` additionally overlaps: all
        three paths route raw feeds through ``prefetch_feeds`` so batch
        N+1's host pulls run on the session's worker while batch N
        dispatches (``BeginIteration`` then fires after its batch's
        feed was prepared — preparation is ahead of the loop by
        design), and a session with ``async_push > 0`` applies pushes
        on a session worker with ``flush()`` barriers at every
        checkpoint export, every ``test()`` pull, and train() end.
        With ``checkpoint_dir`` the session's tables ride inside every
        checkpoint (``Checkpointer(state_vars=...)``) and restore on
        ``resume``.  Not combinable with ``elastic`` or ``warmup``.
        """
        event_handler = event_handler or (lambda e: None)
        if not checkpoint_dir:
            # fail loudly, not silently unprotected: every one of these
            # asks for checkpointing machinery that needs a directory
            if resume:
                raise ValueError("train(resume=True) requires "
                                 "checkpoint_dir")
            if save_every_n_steps is not None:
                raise ValueError("train(save_every_n_steps=...) requires "
                                 "checkpoint_dir")
            if master is not None:
                raise ValueError("train(master=...) snapshots the task "
                                 "queue into checkpoints — pass "
                                 "checkpoint_dir")
            if elastic is not None:
                raise ValueError("train(elastic=...) commits its stream "
                                 "position inside checkpoints — pass "
                                 "checkpoint_dir")
        if elastic is not None and (pipeline or steps_per_dispatch > 1):
            raise ValueError(
                "train(elastic=...) needs the per-batch dispatch path "
                "(steps_per_dispatch=1, pipeline=False): the elastic "
                "task-commit protocol saves at every batch boundary")
        sess = sparse_tables
        if sess is not None:
            if elastic is not None:
                raise NotImplementedError(
                    "train(sparse_tables=..., elastic=...): the elastic "
                    "resize merge has no in-process sparse-row story — "
                    "host the rows outside the worker fleet instead: "
                    "bind a RemoteSparseTable against a pserver fleet "
                    "(python -m paddle_tpu pserver) so workers come and "
                    "go while the row store stays put")
            if warmup:
                raise ValueError(
                    "train(sparse_tables=..., warmup=True) is not "
                    "supported: warmup compiles from a raw peeked batch "
                    "without the session's injected rows feeds")
            sess.bind(self.main_program)
        if auto_shard:
            self._enable_auto_shard(auto_shard)
        # validate is a PER-CALL override: restore the executor's own
        # setting afterwards so a later train() with the default None
        # defers to the flag again
        prev_validate = self.exe.validate
        if validate is not None:
            self.exe.validate = validate
        # autotune is the same kind of per-call override (the executor's
        # own dispatch paths consult _autotuning() for their tuned knobs)
        prev_autotune = self.exe.autotune
        if autotune is not None:
            self.exe.autotune = autotune
        ckpt = None
        try:
            if not self._initialized:
                self.exe.run(default_startup_program(), feed={}, fetch_list=[])
                self._initialized = True

            start_pass, resume_skip = 0, 0
            if checkpoint_dir:
                from .train_state import Checkpointer
                opt_fp = {"type": type(self.optimizer).__name__}
                lr = getattr(self.optimizer, "_learning_rate", None)
                if isinstance(lr, (int, float)):
                    opt_fp["learning_rate"] = float(lr)
                ckpt = Checkpointer(checkpoint_dir, self.exe,
                                    save_every_n_steps=save_every_n_steps,
                                    master=master,
                                    handle_signals=handle_signals,
                                    extra_state=(elastic.state
                                                 if elastic is not None
                                                 else None),
                                    state_vars=(sess.export_state_vars
                                                if sess is not None
                                                else None),
                                    delta_source=sess)
                ts = None
                if resume:
                    ts = ckpt.restore(
                        global_scope(),
                        expect_seed=self.main_program.random_seed,
                        expect_optimizer=opt_fp)
                if ts is not None and sess is not None:
                    # table rows/slots rode the checkpoint as synthetic
                    # __sparse__/ scope vars; pop them into the session's
                    # tables so the host state resumes atomically with
                    # the model
                    if not sess.restore_from_scope(global_scope()):
                        raise ValueError(
                            "train(resume=True, sparse_tables=...): the "
                            "restored checkpoint carries no sparse-table "
                            "state — it was written by a run without "
                            "sparse_tables")
                if ts is not None:
                    # the step counter IS the per-step RNG derivation
                    # state: restoring it restores every random op's
                    # key stream exactly
                    self.exe._step = ts.exe_step
                    start_pass, resume_skip = ts.pass_id, ts.batch_id
                    if master is not None and ts.master is not None \
                            and hasattr(master, "load_state_dict"):
                        # queue position from INSIDE the checkpoint —
                        # atomically consistent with the model restored
                        master.load_state_dict(ts.master)
                ckpt.begin(global_scope(), ts,
                           self.main_program.random_seed, opt_fp)
                if elastic is not None:
                    # register with the membership layer and rewind the
                    # master-sharded stream to the COMMITTED position;
                    # the stream resumes by task re-serve + within-task
                    # offset, so the batch-skip fast-forward must not
                    # also skip (it would double-skip the replay).  The
                    # pass cursor is also stream-defined: a drained
                    # worker's final state says pass_id=num_passes, but
                    # its shard may still hold work (or regain some
                    # after a resize) — always re-enter the pass loop
                    # and let the master decide whether anything is
                    # left (an already-complete worker pulls nothing
                    # and final_save's idempotency skips the re-commit)
                    elastic.bind(ckpt, ts)
                    start_pass, resume_skip = 0, 0

            fetch = [self.cost] + self.extra
            n_fetch = len(fetch)
            # sparse sessions fetch each table's dense <rows>@GRAD
            # alongside the model fetches; `finish` pushes them back to
            # the host tables and strips them before events fire
            sfetch = fetch + (sess.grad_fetch_list if sess is not None
                              else [])

            def finish(out):
                if sess is None:
                    return out
                sess.complete(out[n_fetch:])
                return out[:n_fetch]

            # resolve the pipelined-loop knobs ONCE — including the
            # autotuned fills — so warmup AOT-compiles the exact scan
            # variant the loop will dispatch (_dispatch_k's contract;
            # resolving inside the loop body would let warmup compile
            # the untuned K and the first real dispatch pay the stall)
            pipe_opts = None
            if pipeline:
                pipe_opts = dict(pipeline) if isinstance(pipeline, dict) \
                    else {}
                if self.exe._autotuning():
                    self._fill_tuned_pipeline_opts(pipe_opts,
                                                   steps_per_dispatch)
            if warmup:
                self._warmup(reader, feeding, feed_list, fetch,
                             steps_per_dispatch,
                             pipe_opts if pipe_opts is not None else False)

            # periodic observability reports every `log_period` iterations
            # (the v1 Stat::printAllStatus cadence, Flags.cpp:62), counted
            # across passes (and across restarts when resuming); no-op
            # unless observing
            iters_done = ckpt.iters_done if ckpt is not None else 0
            observing = self.exe._observing()
            # global batch cursor (across passes AND restarts): the index
            # key of the trainer.step/reader.item injection sites, so a
            # resumed run never re-fires a spec entry it already passed
            gcount = [ckpt.emitted if ckpt is not None else 0]

            def emit_end(pass_id, batch_id, out):
                nonlocal iters_done
                # step snapshot BEFORE the handler runs: a handler that
                # does extra executor work (trainer.test) must not blur
                # this batch's dispatch-boundary detection
                step_now = self.exe._step
                metrics = {getattr(v, "name", str(i)): out[1 + i]
                           for i, v in enumerate(self.extra)}
                event_handler(events.EndIteration(
                    pass_id, batch_id, float(out[0]), metrics))
                iters_done += 1
                observability.maybe_periodic_report(iters_done,
                                                    observing=observing)
                gcount[0] += 1
                if _fi.ENABLED:
                    action = _fi.check("trainer.step", index=gcount[0])
                    if action == "preempt":
                        if ckpt is None:
                            # fail loudly: the spec asked for a graceful
                            # preemption this run cannot perform
                            raise _fi.InjectedFault(
                                "trainer.step=preempt injected but "
                                "train() has no checkpoint_dir")
                        ckpt.request_preempt()
                    elif action == "sigterm":
                        os.kill(os.getpid(), _signal.SIGTERM)
                    elif action == "kill":
                        # REAL SIGKILL: dies with returncode -9, which a
                        # supervisor treats as relaunchable signal death
                        os.kill(os.getpid(), _signal.SIGKILL)
                    elif action is not None:
                        _fi.raise_for(action, "trainer.step", gcount[0])
                if ckpt is not None:
                    ckpt.on_batch_done(pass_id, batch_id, step_now)
                if elastic is not None:
                    elastic.after_batch()

            # reader wrapper: resume skip for the first resumed pass +
            # the reader.item injection site.  The plain path stays the
            # raw reader — zero new per-step work when fault tolerance
            # and injection are off.
            rcount = [gcount[0]]

            def pass_reader(pass_id):
                skip = resume_skip if pass_id == start_pass else 0
                if skip == 0 and not _fi.ENABLED:
                    return reader, 0

                def _r():
                    for i, b in enumerate(reader()):
                        if i < skip:
                            continue
                        rcount[0] += 1
                        if _fi.ENABLED:
                            a = _fi.check("reader.item", index=rcount[0])
                            if a is not None:
                                _fi.raise_for(a, "reader.item", rcount[0])
                        yield b
                return _r, skip

            if pipeline:
                opts = pipe_opts
                K = self._dispatch_k(opts, steps_per_dispatch)
                workers = int(opts.get("num_workers", 1))
                buf = int(opts.get("buffer_size", 4))
                depth = int(opts.get("prefetch_depth", 2))
                # feed() results live at most until their chunk is stacked /
                # shipped — K pending plus in-flight slack bounds liveness
                feeder = self._feeder(feeding, feed_list, staging_slots=K + 2)
                from .reader.pipeline import prefetch
                for pass_id in range(start_pass, num_passes):
                    event_handler(events.BeginPass(pass_id))
                    if ckpt is not None:
                        ckpt.resync()
                    # num_workers=0: no reader prefetch stage — decode runs in
                    # run_pipelined's staging thread (one host thread total;
                    # right when host cores are scarce)
                    r, skip = pass_reader(pass_id)
                    src = prefetch(r, buffer_size=buf,
                                   num_workers=workers) if workers > 0 \
                        else r
                    feed_iter = (feeder.feed(b) for b in src())
                    if sess is not None:
                        # pulls run ahead of the pushes (the staging
                        # thread — plus the session's own pull-ahead
                        # worker when prefetch_depth > 0): bounded-
                        # staleness async updates (see docstring)
                        if getattr(sess, "prefetch_depth", 0) > 0:
                            feed_iter = sess.prefetch_feeds(feed_iter)
                        else:
                            feed_iter = (sess.prepare_feed(f)
                                         for f in feed_iter)
                    gen = self.exe.run_pipelined(
                        feed_iter, self.main_program, fetch_list=sfetch,
                        steps_per_dispatch=K, prefetch_depth=depth)
                    try:
                        for batch_id, out in enumerate(gen, start=skip):
                            out = finish(out)
                            event_handler(events.BeginIteration(pass_id,
                                                                batch_id))
                            emit_end(pass_id, batch_id, out)
                    finally:
                        # a mid-pass failure must deterministically stop
                        # the whole feed chain, not wait for GC: close
                        # the pipelined generator FIRST (its contract
                        # stops and joins the staging worker that may be
                        # executing feed_iter right now — closing
                        # feed_iter before that join would race a
                        # running generator), then the feed source (the
                        # session's pull-ahead worker, when prefetching)
                        gen.close()
                        feed_iter.close()
                    event_handler(events.EndPass(pass_id))
                if sess is not None and hasattr(sess, "flush"):
                    sess.flush()     # async-push barrier at train end
                if ckpt is not None:
                    ckpt.final_save(num_passes)
                return

            feeder = self._feeder(feeding, feed_list)

            def flush(pass_id, first_id, chunk):
                if len(chunk) == 1:
                    event_handler(events.BeginIteration(pass_id, first_id))
                    out = finish(self.exe.run(
                        self.main_program, feed=chunk[0],
                        fetch_list=sfetch))
                    emit_end(pass_id, first_id, out)
                    return
                from .core.executor import stack_feeds
                stacked = stack_feeds(chunk)
                outs = self.exe.run_steps(
                    len(chunk), self.main_program, feed=stacked,
                    fetch_list=sfetch, feeds_stacked=True)
                for i in range(len(chunk)):
                    event_handler(events.BeginIteration(pass_id, first_id + i))
                    emit_end(pass_id, first_id + i,
                             finish([o[i] for o in outs]))

            for pass_id in range(start_pass, num_passes):
                event_handler(events.BeginPass(pass_id))
                if ckpt is not None:
                    ckpt.resync()
                r, skip = pass_reader(pass_id)
                sess_prefetch = sess is not None and \
                    getattr(sess, "prefetch_depth", 0) > 0
                if steps_per_dispatch <= 1:
                    if sess_prefetch:
                        # pull-ahead rim: batch N+1's host pulls run on
                        # the session worker while batch N dispatches
                        feeds = sess.prefetch_feeds(
                            feeder.feed(b) for b in r())
                        try:
                            for batch_id, feed in enumerate(feeds,
                                                            start=skip):
                                event_handler(events.BeginIteration(
                                    pass_id, batch_id))
                                out = finish(self.exe.run(
                                    self.main_program, feed=feed,
                                    fetch_list=sfetch))
                                emit_end(pass_id, batch_id, out)
                        finally:
                            feeds.close()
                        event_handler(events.EndPass(pass_id))
                        continue
                    for batch_id, batch in enumerate(r(), start=skip):
                        event_handler(events.BeginIteration(pass_id, batch_id))
                        feed = feeder.feed(batch)
                        if sess is not None:
                            # synchronous rim: pull -> step -> push
                            feed = sess.prepare_feed(feed)
                        out = finish(self.exe.run(self.main_program,
                                                  feed=feed,
                                                  fetch_list=sfetch))
                        emit_end(pass_id, batch_id, out)
                    event_handler(events.EndPass(pass_id))
                    continue
                if sess is None:
                    feed_src = (feeder.feed(b) for b in r())
                elif sess_prefetch:
                    # pull-ahead rim over the chunked path
                    feed_src = sess.prefetch_feeds(
                        feeder.feed(b) for b in r())
                else:
                    # chunk-granular staleness: all K pulls precede
                    # the chunk's dispatch (async-pserver semantics)
                    feed_src = (sess.prepare_feed(feeder.feed(b))
                                for b in r())
                chunk, first_id, sig = [], 0, None
                try:
                    for batch_id, feed in enumerate(feed_src, start=skip):
                        fsig = tuple(sorted(
                            (k, np.shape(v), str(np.asarray(v).dtype))
                            for k, v in feed.items()))
                        if chunk and fsig != sig:
                            flush(pass_id, first_id, chunk)
                            chunk = []
                        if not chunk:
                            first_id, sig = batch_id, fsig
                        chunk.append(feed)
                        if len(chunk) == steps_per_dispatch:
                            flush(pass_id, first_id, chunk)
                            chunk = []
                    if chunk:
                        flush(pass_id, first_id, chunk)
                finally:
                    feed_src.close()
                event_handler(events.EndPass(pass_id))
            if sess is not None and hasattr(sess, "flush"):
                sess.flush()         # async-push barrier at train end
            if ckpt is not None:
                ckpt.final_save(num_passes)
            if elastic is not None:
                # the final save above committed the last task's state;
                # the hook now reports it finished and deregisters
                elastic.on_complete()
        finally:
            self.exe.validate = prev_validate
            self.exe.autotune = prev_autotune
            if ckpt is not None:
                ckpt.close()

    def test(self, reader: Callable, feeding=None, feed_list=None,
             sparse_tables=None):
        """Average cost (+extras) over a reader without updating params.
        ``sparse_tables``: the training session — evaluation pulls rows
        read-only (no grad fetches, no pushes)."""
        feeder = self._feeder(feeding, feed_list)
        test_prog = self.main_program.prune(
            [self.cost] + self.extra).clone(for_test=True)
        if sparse_tables is not None:
            sparse_tables.bind(test_prog)
        totals, count = None, 0
        for batch in reader():
            feed = feeder.feed(batch)
            if sparse_tables is not None:
                feed = sparse_tables.prepare_feed(feed, is_test=True)
            out = self.exe.run(test_prog, feed=feed,
                               fetch_list=[self.cost] + self.extra,
                               is_test=True)
            vals = [np.asarray(o, np.float64) for o in out]
            totals = vals if totals is None else [
                t + v for t, v in zip(totals, vals)]
            count += 1
        if count == 0:
            return None
        return [t / count for t in totals]

    # -- helpers -----------------------------------------------------------
    def _enable_auto_shard(self, auto_shard):
        """Resolve the train(auto_shard=) forms onto the executor."""
        from .parallel.sharded import ShardedExecutor

        if isinstance(self.exe, ShardedExecutor):
            if auto_shard is not True:
                # a mesh form alongside an existing ShardedExecutor must
                # AGREE with its mesh — silently planning for the
                # executor's mesh while the user asked for another would
                # misreport what ran
                if isinstance(auto_shard, str):
                    from .cli import _parse_mesh
                    want = _parse_mesh(auto_shard)
                else:
                    want = {str(k): int(v)
                            for k, v in dict(auto_shard).items()}
                have = {str(a): int(self.exe.mesh.shape[a])
                        for a in self.exe.mesh.axis_names
                        if self.exe.mesh.shape[a] > 1}
                if {k: v for k, v in want.items() if v > 1} != have:
                    raise ValueError(
                        f"train(auto_shard={auto_shard!r}) conflicts "
                        f"with the executor's existing mesh {have} — "
                        f"pass auto_shard=True to plan for that mesh, "
                        f"or build the trainer without a ShardedExecutor")
            self.exe.auto_shard = True
            return
        if auto_shard is True:
            raise ValueError(
                "train(auto_shard=True) needs a ShardedExecutor (its mesh "
                "is the planning target); pass a mesh instead — "
                "auto_shard={'dp': 8} or auto_shard='dp=8,tp=2'")
        if self._initialized:
            raise ValueError(
                "train(auto_shard=<mesh>) must be given on the FIRST "
                "train() call: parameters were already initialized on the "
                "unsharded executor")
        if isinstance(auto_shard, str):
            from .cli import _parse_mesh
            axes = _parse_mesh(auto_shard)
        else:
            axes = {str(k): int(v) for k, v in dict(auto_shard).items()}
        from .parallel.mesh import mesh_for_axes
        self.exe = ShardedExecutor(
            mesh=mesh_for_axes(axes), batch_axis=next(iter(axes), "dp"),
            auto_shard=True)

    def _fill_tuned_pipeline_opts(self, opts, steps_per_dispatch):
        """Fill OMITTED pipeline knobs from persisted autotuner winners
        (autotune opt-in resolved by the caller).  Explicit knobs — in
        the pipeline dict, or steps_per_dispatch > 1 as the documented
        K override — always win; with no persisted record every knob
        resolves to its existing hand-picked default, so this is
        behavior-neutral until a `tune` run has committed a winner."""
        pipe = self.exe._tuned(
            "executor/run_pipelined",
            {"steps_per_dispatch": 8, "prefetch_depth": 2})
        if "steps_per_dispatch" not in opts and steps_per_dispatch <= 1:
            opts["steps_per_dispatch"] = pipe["steps_per_dispatch"]
        if "prefetch_depth" not in opts:
            opts["prefetch_depth"] = pipe["prefetch_depth"]
        rd = self.exe._tuned("reader/prefetch",
                             {"num_workers": 1, "buffer_size": 4})
        if "num_workers" not in opts:
            opts["num_workers"] = rd["num_workers"]
        if "buffer_size" not in opts:
            opts["buffer_size"] = rd["buffer_size"]

    @staticmethod
    def _dispatch_k(opts, steps_per_dispatch):
        """Steps per pipelined dispatch — ONE derivation shared by the
        train loop and _warmup, so warmup always AOT-compiles the exact
        scan variant the loop will dispatch."""
        return int(opts.get("steps_per_dispatch",
                            steps_per_dispatch if steps_per_dispatch > 1
                            else 8))

    def _warmup(self, reader, feeding, feed_list, fetch,
                steps_per_dispatch, pipeline):
        """AOT-compile the step variant(s) the configured loop will use,
        from the shapes of one peeked batch (the batch itself is NOT
        consumed from the training stream — readers are re-callable)."""
        probe = next(iter(reader()), None)
        if probe is None:
            return
        feed0 = self._feeder(feeding, feed_list).feed(probe)
        # train() passes the RESOLVED opts dict (autotuned fills applied)
        # when pipelining — an empty dict still means "pipelined"
        if pipeline is not False and pipeline is not None:
            opts = dict(pipeline) if isinstance(pipeline, dict) else {}
            K = self._dispatch_k(opts, steps_per_dispatch)
        else:
            K = steps_per_dispatch
        # single-step variant: the per-batch path, and the tail/signature-
        # change fallback of the chunked paths
        self.exe.compile(self.main_program, feed=feed0, fetch_list=fetch)
        if K > 1:
            from .core.executor import stack_feeds
            self.exe.compile(self.main_program,
                             feed=stack_feeds([feed0] * K),
                             fetch_list=fetch, num_steps=K,
                             feeds_stacked=True)

    def _feeder(self, feeding, feed_list, staging_slots: int = 0):
        if feed_list is None:
            gb = self.main_program.global_block()
            # session_feed vars (sparse-table rows/inverse) are injected
            # by the SparseSession rim, never by the reader
            data_vars = [v for v in gb.vars.values()
                         if v.is_data and not v.session_feed]
            if feeding is not None:
                order = sorted(feeding, key=lambda k: feeding[k])
                feed_list = [gb.var(n) for n in order]
            else:
                feed_list = data_vars
        return DataFeeder(feed_list, staging_slots=staging_slots)

    def save_parameter_to_tar(self, f=None, dirname=None):
        from . import io
        io.save_params(self.exe, dirname or f, self.main_program)


def infer(output_layer, parameters=None, input=None, feeding=None,
          feed_list=None, executor=None, program: Optional[Program] = None):
    """v2 paddle.infer analog: run the pruned inference slice on a batch."""
    outputs = output_layer if isinstance(output_layer, (list, tuple)) \
        else [output_layer]
    program = program or outputs[0].block.program
    infer_prog = program.prune(outputs).clone(for_test=True)
    exe = executor or Executor()
    gb = program.global_block()
    if feed_list is None:
        if feeding is not None:
            order = sorted(feeding, key=lambda k: feeding[k])
            feed_list = [gb.var(n) for n in order]
        else:
            feed_list = [v for v in gb.vars.values()
                         if v.is_data and not v.session_feed]
    # keep only feeds the pruned program actually reads
    needed = set()
    for op in infer_prog.global_block().ops:
        needed.update(op.input_names)
    feed_list = [v for v in feed_list if v.name in needed]
    feeder = DataFeeder(feed_list)
    feed = feeder.feed(input)
    res = exe.run(infer_prog, feed=feed, fetch_list=outputs, is_test=True)
    return res if len(res) > 1 else res[0]
