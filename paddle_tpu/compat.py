"""JAX cross-version compatibility shims.

The public JAX surface this framework leans on moved between the 0.4.x
line and newer releases:

* ``jax.shard_map`` (new, with ``axis_names=``/``check_vma=`` partial-manual
  kwargs) vs ``jax.experimental.shard_map.shard_map`` (old, with
  ``auto=``/``check_rep=`` spelled from the opposite direction);
* ``jax.lax.axis_size`` (new) vs the ``lax.psum(1, axis)`` constant-folding
  idiom (old);
* ``jax.lax.pvary`` (new varying-manual-axes type system) with no old
  counterpart — on old JAX replication is inferred, so it is the identity;
* ``jax.sharding.AxisType`` + ``get_abstract_mesh`` (new) vs the axis-env
  trace state (old) for detecting a surrounding shard_map manual region.

Everything that needs one of these APIs imports it from here, so exactly
one module knows which JAX it is running on.  Resolution happens at call
time (not import time): the shims stay importable even if a future JAX
moves the surface again, failing only at the call site with a clear error.
"""
from __future__ import annotations

from typing import Optional

import jax
from jax import lax

__all__ = ["shard_map", "axis_size", "pvary", "manual_axes",
           "executable_cost_analysis", "executable_memory_analysis"]


def shard_map(f, mesh, in_specs, out_specs, axis_names=None, check_vma=None):
    """``jax.shard_map`` surface on every supported JAX.

    ``axis_names``: the mesh axes the body is manual over (new-API
    spelling); every other mesh axis stays auto/GSPMD-managed.  On old JAX
    this maps to ``auto = mesh.axis_names - axis_names``.  ``check_vma``
    maps to old ``check_rep`` (same role: verify replication/varying
    claims; both sides accept False to opt out).
    """
    sm = getattr(jax, "shard_map", None)
    if sm is not None:
        kw = {}
        if axis_names is not None:
            kw["axis_names"] = frozenset(axis_names)
        if check_vma is not None:
            kw["check_vma"] = check_vma
        return sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, **kw)
    from jax.experimental.shard_map import shard_map as esm  # old JAX
    # No ``auto=``: old partial-auto lowers lax.axis_index to a PartitionId
    # instruction the SPMD partitioner rejects ("meaning is ambiguous").
    # Going full-manual instead is always numerically correct — axes the
    # body never names are simply replicated through it (in_specs leaving
    # them unmentioned), at the cost of redundant compute over those axes
    # on multi-device meshes.  Only the old-JAX fallback pays this.
    kw = {}
    if check_vma is not None:
        kw["check_rep"] = bool(check_vma)
    return esm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, **kw)


def axis_size(axis_name) -> int:
    """Size of a bound mesh axis inside shard_map/pmap.

    Old JAX: ``lax.psum`` of a non-tracer constant folds to the axis size
    without emitting a collective — the pre-``lax.axis_size`` idiom.
    Raises ``NameError`` for an unbound axis name on both paths.
    """
    fn = getattr(lax, "axis_size", None)
    if fn is not None:
        return fn(axis_name)
    return lax.psum(1, axis_name)


def pvary(x, axis_names):
    """Mark ``x`` device-varying over ``axis_names`` (new shard_map type
    system).  Old JAX infers replication and has no varying-manual-axes
    types, so there the identity is exactly right — autodiff inside a
    shard_map body never inserts the psum-of-replicated-cotangents the
    new system needs ``pvary`` to elide."""
    fn = getattr(lax, "pvary", None)
    if fn is not None:
        return fn(x, axis_names)
    return x


def manual_axes() -> Optional[frozenset]:
    """Mesh axes currently bound manual (i.e. we are tracing inside a
    shard_map body): frozenset of names, empty when outside.  Returns
    ``None`` when no known JAX API can answer — callers should treat that
    as "unknown" and degrade loudly, not assume "outside"."""
    try:  # new JAX: abstract mesh carries per-axis Manual/Auto types
        from jax.sharding import AxisType
        am = jax.sharding.get_abstract_mesh()
        return frozenset(n for n, t in zip(am.axis_names, am.axis_types)
                         if t == AxisType.Manual)
    except (ImportError, AttributeError):
        pass
    try:  # old JAX: shard_map binds its axes in the trace-state axis env
        from jax._src import core as _core
        env = _core.get_axis_env()
        return frozenset(env.axis_sizes)
    except (ImportError, AttributeError):
        pass
    return None


def executable_cost_analysis(compiled) -> Optional[dict]:
    """XLA cost analysis of a compiled executable, normalized to one flat
    ``{"flops": ..., "bytes_accessed": ..., ...}`` dict.

    The surface drifted across jax releases: ``Compiled.cost_analysis()``
    returns a list with one dict per partition on the 0.4.x line and a
    bare dict on newer jax; some backends (and serialized-executable
    reloads) raise or return nothing.  ``None`` means "unavailable" —
    callers fall back to the static cost model, never crash.
    """
    fn = getattr(compiled, "cost_analysis", None)
    if fn is None:
        return None
    try:
        ca = fn()
    except Exception:   # backend without the analysis API
        return None
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else None
    if not isinstance(ca, dict) or not ca:
        return None
    out = {}
    for k in ("flops", "transcendentals", "bytes accessed",
              "bytes_accessed", "optimal_seconds"):
        v = ca.get(k)
        if isinstance(v, (int, float)):
            out[k.replace(" ", "_")] = float(v)
    return out or None


def executable_memory_analysis(compiled) -> Optional[dict]:
    """``Compiled.memory_analysis()`` normalized to plain ints (the
    return type is an opaque ``CompiledMemoryStats`` on this jax line, a
    dict-like on others).  ``None`` when unavailable."""
    fn = getattr(compiled, "memory_analysis", None)
    if fn is None:
        return None
    try:
        ma = fn()
    except Exception:   # backend without the analysis API
        return None
    if ma is None:
        return None
    out = {}
    for k in ("argument_size_in_bytes", "output_size_in_bytes",
              "temp_size_in_bytes", "generated_code_size_in_bytes",
              "alias_size_in_bytes"):
        v = getattr(ma, k, None) if not isinstance(ma, dict) else ma.get(k)
        if isinstance(v, (int, float)):
            out[k] = int(v)
    return out or None
