"""Shared helpers for dataset modules."""
from __future__ import annotations

import numpy as np


def synthetic_classification(n, feat_shape, num_classes, seed,
                             flatten=False, proto_seed=None):
    """Deterministic synthetic labeled data with learnable structure: class
    k's examples cluster around a fixed random prototype.  ``proto_seed``
    pins the prototypes so train/test splits share the distribution."""
    rng = np.random.RandomState(seed if proto_seed is None else proto_seed)
    protos = rng.rand(num_classes, *feat_shape).astype("float32")

    def reader():
        r = np.random.RandomState(seed + 1)
        for _ in range(n):
            y = int(r.randint(num_classes))
            x = protos[y] + 0.1 * r.randn(*feat_shape).astype("float32")
            yield (x.reshape(-1) if flatten else x, y)
    return reader


def synthetic_sequences(n, vocab_size, num_classes, seed, min_len=4,
                        max_len=20):
    """Token sequences whose label is derivable from the first token."""
    def reader():
        r = np.random.RandomState(seed)
        for _ in range(n):
            L = int(r.randint(min_len, max_len + 1))
            toks = r.randint(2, vocab_size, L).tolist()
            y = int(toks[0] * num_classes // vocab_size)
            yield toks, y
    return reader
