"""Shared helpers for dataset modules: md5-cached download, file split
utilities (reference: v2/dataset/common.py — DATA_HOME:34, download:63
retry + md5 verify, split:110, cluster_files_reader:140), plus the
deterministic synthetic generators that keep CI hermetic when the real
archives are absent."""
from __future__ import annotations

import errno
import glob
import hashlib
import os
import pickle

import numpy as np

DATA_HOME = os.environ.get(
    "PADDLE_TPU_DATA_HOME",
    os.path.expanduser("~/.cache/paddle_tpu/dataset"))


def must_mkdirs(path):
    """mkdir -p that tolerates concurrent creators (common.py:41)."""
    try:
        os.makedirs(path)
    except OSError as exc:
        if exc.errno != errno.EEXIST:
            raise


def md5file(fname, chunk=1 << 20):
    h = hashlib.md5()
    with open(fname, "rb") as f:
        for c in iter(lambda: f.read(chunk), b""):
            h.update(c)
    return h.hexdigest()


def download(url, module_name, md5sum, retry_limit=3):
    """Fetch ``url`` into DATA_HOME/module_name with md5 verification and
    retries; return the cached path (common.py:63).  A file already present
    with the right md5 is never re-fetched, so offline runs that have the
    cache (or that pre-populate it from local media / file:// URLs) work
    without network."""
    import urllib.request

    dirname = os.path.join(DATA_HOME, module_name)
    must_mkdirs(dirname)
    filename = os.path.join(dirname, url.split("/")[-1])
    retry = 0
    last_err = None
    while not (os.path.exists(filename) and md5file(filename) == md5sum):
        if retry >= retry_limit:
            raise RuntimeError(
                f"cannot download {url} within {retry_limit} retries "
                f"(md5 mismatch or unreachable; last error: {last_err})")
        retry += 1
        tmp = filename + ".part"
        try:
            with urllib.request.urlopen(url) as r, open(tmp, "wb") as out:
                for chunk in iter(lambda: r.read(1 << 20), b""):
                    out.write(chunk)
            os.replace(tmp, filename)
        except OSError as e:          # URLError subclasses OSError
            last_err = e
            if os.path.exists(tmp):
                os.remove(tmp)
    return filename


def cached_path(url, module_name, md5sum, do_download=False):
    """The one cache probe every dataset module shares: the md5-verified
    cached file if present; else fetch it when ``do_download``; else None
    (callers fall back to their synthetic generators).  Real data is only
    ever used on EXPLICIT request — a populated cache must not silently
    change what a default reader yields."""
    if not do_download:
        return None
    filename = os.path.join(DATA_HOME, module_name, url.split("/")[-1])
    if os.path.exists(filename) and md5file(filename) == md5sum:
        return filename
    return download(url, module_name, md5sum)


def split(reader, line_count, suffix="%05d.pickle", dumper=None):
    """Split a reader's samples into pickle files of ``line_count`` samples
    (common.py:110 — the cluster-job data prep step)."""
    dumper = dumper or (lambda data, f: pickle.dump(data, f))
    indx_f = 0
    buf = []
    for sample in reader():
        buf.append(sample)
        if len(buf) == line_count:
            with open(suffix % indx_f, "wb") as f:
                dumper(buf, f)
            buf = []
            indx_f += 1
    if buf:
        with open(suffix % indx_f, "wb") as f:
            dumper(buf, f)


def cluster_files_reader(files_pattern, trainer_count, trainer_id,
                         loader=None):
    """Read this trainer's round-robin share of split files
    (common.py:140)."""
    loader = loader or (lambda f: pickle.load(f))

    def reader():
        flist = sorted(glob.glob(files_pattern))
        for idx, fn in enumerate(flist):
            if idx % trainer_count == trainer_id:
                with open(fn, "rb") as f:
                    for sample in loader(f):
                        yield sample
    return reader


def synthetic_classification(n, feat_shape, num_classes, seed,
                             flatten=False, proto_seed=None):
    """Deterministic synthetic labeled data with learnable structure: class
    k's examples cluster around a fixed random prototype.  ``proto_seed``
    pins the prototypes so train/test splits share the distribution."""
    rng = np.random.RandomState(seed if proto_seed is None else proto_seed)
    protos = rng.rand(num_classes, *feat_shape).astype("float32")

    def reader():
        r = np.random.RandomState(seed + 1)
        for _ in range(n):
            y = int(r.randint(num_classes))
            x = protos[y] + 0.1 * r.randn(*feat_shape).astype("float32")
            yield (x.reshape(-1) if flatten else x, y)
    return reader


def synthetic_sequences(n, vocab_size, num_classes, seed, min_len=4,
                        max_len=20):
    """Token sequences whose label is derivable from the first token."""
    def reader():
        r = np.random.RandomState(seed)
        for _ in range(n):
            L = int(r.randint(min_len, max_len + 1))
            toks = r.randint(2, vocab_size, L).tolist()
            y = int(toks[0] * num_classes // vocab_size)
            yield toks, y
    return reader
