"""IMDB sentiment reader (reference: v2/dataset/imdb.py — aclImdb tar
tokenizer, frequency-cutoff dictionary, shuffled pos/neg reader; synthetic
fallback for offline CI)."""
from __future__ import annotations

import collections
import os
import re
import string
import tarfile

import numpy as np

from .common import cached_path, synthetic_sequences

URL = "http://ai.stanford.edu/%7Eamaas/data/sentiment/aclImdb_v1.tar.gz"
MD5 = "7c2ac02c03563afcf9b574c7e56c153a"
VOCAB_SIZE = 5000

TRAIN_POS = re.compile(r"aclImdb/train/pos/.*\.txt$")
TRAIN_NEG = re.compile(r"aclImdb/train/neg/.*\.txt$")
TEST_POS = re.compile(r"aclImdb/test/pos/.*\.txt$")
TEST_NEG = re.compile(r"aclImdb/test/neg/.*\.txt$")

_PUNCT = str.maketrans("", "", string.punctuation)


_DICT_MEMO = {}


def _archive(do_download=False):
    return cached_path(URL, "imdb", MD5, do_download)


def tokenize(pattern, archive_path):
    """Sequential tar walk (imdb.py:35 — tarfile.next, not random access),
    yielding the lowercase punctuation-stripped token list per document."""
    with tarfile.open(archive_path) as tarf:
        tf = tarf.next()
        while tf is not None:
            if pattern.match(tf.name):
                text = tarf.extractfile(tf).read().decode(
                    "utf-8", errors="ignore")
                yield text.rstrip("\n\r").translate(_PUNCT).lower().split()
            tf = tarf.next()


def build_dict(pattern=None, cutoff=150, download=False):
    """Frequency-cutoff word dict (imdb.py:56): ids ordered by (-freq,
    word), '<unk>' last.  Falls back to the synthetic vocab offline."""
    archive = _archive(download)
    if archive is None:
        return {f"w{i}": i for i in range(VOCAB_SIZE)}
    memo_key = (archive, cutoff, pattern.pattern if pattern else None)
    if memo_key in _DICT_MEMO:
        return _DICT_MEMO[memo_key]
    if pattern is None:
        pattern = re.compile(
            r"aclImdb/((train)|(test))/((pos)|(neg))/.*\.txt$")
    word_freq = collections.defaultdict(int)
    for doc in tokenize(pattern, archive):
        for w in doc:
            word_freq[w] += 1
    items = [(w, f) for w, f in word_freq.items() if f > cutoff]
    items.sort(key=lambda x: (-x[1], x[0]))
    word_idx = {w: i for i, (w, _) in enumerate(items)}
    word_idx["<unk>"] = len(word_idx)
    _DICT_MEMO[memo_key] = word_idx
    return word_idx


word_dict = build_dict


def _reader_creator(pos_pattern, neg_pattern, word_idx, archive, seed):
    UNK = word_idx.get("<unk>", len(word_idx) - 1)

    def reader():
        ins = []
        for doc in tokenize(pos_pattern, archive):
            ins.append(([word_idx.get(w, UNK) for w in doc], 0))
        for doc in tokenize(neg_pattern, archive):
            ins.append(([word_idx.get(w, UNK) for w in doc], 1))
        np.random.RandomState(seed).shuffle(ins)
        yield from ins
    return reader


def train(word_idx=None, download=False):
    archive = _archive(download)
    if archive is None:
        v = len(word_idx) if word_idx else VOCAB_SIZE
        return synthetic_sequences(2000, v, 2, seed=20, min_len=8,
                                   max_len=60)
    word_idx = word_idx or build_dict(download=download)
    return _reader_creator(TRAIN_POS, TRAIN_NEG, word_idx, archive, 0)


def test(word_idx=None, download=False):
    archive = _archive(download)
    if archive is None:
        v = len(word_idx) if word_idx else VOCAB_SIZE
        return synthetic_sequences(400, v, 2, seed=21, min_len=8,
                                   max_len=60)
    word_idx = word_idx or build_dict(download=download)
    return _reader_creator(TEST_POS, TEST_NEG, word_idx, archive, 1)
