"""IMDB sentiment reader (reference: v2/dataset/imdb.py + benchmark
rnn/imdb.py; synthetic fallback)."""
from __future__ import annotations

from .common import synthetic_sequences

VOCAB_SIZE = 5000


def word_dict():
    return {f"w{i}": i for i in range(VOCAB_SIZE)}


def train(word_idx=None):
    v = len(word_idx) if word_idx else VOCAB_SIZE
    return synthetic_sequences(2000, v, 2, seed=20, min_len=8, max_len=60)


def test(word_idx=None):
    v = len(word_idx) if word_idx else VOCAB_SIZE
    return synthetic_sequences(400, v, 2, seed=21, min_len=8, max_len=60)
