"""UCI housing reader (reference: v2/dataset/uci_housing.py; synthetic
linear data with fixed planted weights)."""
from __future__ import annotations

import numpy as np

FEATURES = 13
_W = np.linspace(-2, 2, FEATURES).astype("float32")
_B = 22.5


def _gen(seed, n):
    def reader():
        r = np.random.RandomState(seed)
        for _ in range(n):
            x = r.randn(FEATURES).astype("float32")
            y = float(x @ _W + _B + 0.1 * r.randn())
            yield x, y
    return reader


def train():
    return _gen(50, 400)


def test():
    return _gen(51, 100)
