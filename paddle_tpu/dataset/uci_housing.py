"""UCI housing reader (reference: v2/dataset/uci_housing.py —
whitespace-table parser with per-feature min/max/avg normalization and the
80/20 train/test split; synthetic fallback for offline CI)."""
from __future__ import annotations

import os

import numpy as np

from .common import cached_path

URL = ("https://archive.ics.uci.edu/ml/machine-learning-databases/housing/"
       "housing.data")
MD5 = "d4accdce7a25600298819f8e28e8d593"
FEATURES = 13
feature_names = ["CRIM", "ZN", "INDUS", "CHAS", "NOX", "RM", "AGE", "DIS",
                 "RAD", "TAX", "PTRATIO", "B", "LSTAT"]

_W = np.linspace(-2, 2, FEATURES).astype("float32")
_B = 22.5


def _data_file(do_download=False):
    return cached_path(URL, "uci_housing", MD5, do_download)


def load_data(filename, feature_num=14, ratio=0.8):
    """Parse + normalize (uci_housing.py:61): x <- (x - avg) / (max - min),
    then split 80/20."""
    data = np.fromfile(filename, sep=" ").astype("float32")
    data = data.reshape(-1, feature_num)
    maximums = data.max(axis=0)
    minimums = data.min(axis=0)
    avgs = data.mean(axis=0)
    for i in range(feature_num - 1):
        data[:, i] = (data[:, i] - avgs[i]) / (maximums[i] - minimums[i])
    offset = int(data.shape[0] * ratio)
    return data[:offset], data[offset:]


def _file_reader(rows):
    def reader():
        for row in rows:
            yield row[:-1], float(row[-1])
    return reader


def _gen(seed, n):
    def reader():
        r = np.random.RandomState(seed)
        for _ in range(n):
            x = r.randn(FEATURES).astype("float32")
            y = float(x @ _W + _B + 0.1 * r.randn())
            yield x, y
    return reader


def train(download=False):
    f = _data_file(download)
    if f is None:
        return _gen(50, 400)
    return _file_reader(load_data(f)[0])


def test(download=False):
    f = _data_file(download)
    if f is None:
        return _gen(51, 100)
    return _file_reader(load_data(f)[1])
