"""PTB n-gram LM reader (reference: v2/dataset/imikolov.py; synthetic)."""
from __future__ import annotations

import numpy as np

VOCAB = 2000


def build_dict(min_word_freq=50):
    return {f"w{i}": i for i in range(VOCAB)}


def train(word_idx=None, n=5):
    v = len(word_idx) if word_idx else VOCAB

    def reader():
        r = np.random.RandomState(30)
        for _ in range(3000):
            start = int(r.randint(0, v - n))
            yield tuple(range(start, start + n))   # learnable successor rule
    return reader


def test(word_idx=None, n=5):
    v = len(word_idx) if word_idx else VOCAB

    def reader():
        r = np.random.RandomState(31)
        for _ in range(500):
            start = int(r.randint(0, v - n))
            yield tuple(range(start, start + n))
    return reader
