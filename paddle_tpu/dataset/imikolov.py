"""PTB (Mikolov) LM reader (reference: v2/dataset/imikolov.py —
simple-examples.tgz parser, min-frequency dictionary, NGRAM/SEQ reader
modes; synthetic fallback for offline CI)."""
from __future__ import annotations

import collections
import os
import tarfile

import numpy as np

from .common import cached_path

URL = "http://www.fit.vutbr.cz/~imikolov/rnnlm/simple-examples.tgz"
MD5 = "30177ea32e27c525793142b6bf2c8e2d"
TRAIN_FILE = "./simple-examples/data/ptb.train.txt"
VALID_FILE = "./simple-examples/data/ptb.valid.txt"
VOCAB = 2000


class DataType:
    NGRAM = 1
    SEQ = 2


def _archive(do_download=False):
    return cached_path(URL, "imikolov", MD5, do_download)


def word_count(f, word_freq=None):
    """Line word counts with <s>/<e> sentence markers (imikolov.py:36)."""
    word_freq = word_freq if word_freq is not None else \
        collections.defaultdict(int)
    for line in f:
        if isinstance(line, bytes):
            line = line.decode("utf-8", errors="ignore")
        for w in line.strip().split():
            word_freq[w] += 1
        word_freq["<s>"] += 1
        word_freq["<e>"] += 1
    return word_freq


def build_dict(min_word_freq=50, download=False):
    archive = _archive(download)
    if archive is None:
        return {f"w{i}": i for i in range(VOCAB)}
    with tarfile.open(archive) as tf:
        freq = word_count(tf.extractfile(VALID_FILE),
                          word_count(tf.extractfile(TRAIN_FILE)))
    freq.pop("<unk>", None)
    items = [(w, f) for w, f in freq.items() if f > min_word_freq]
    items.sort(key=lambda x: (-x[1], x[0]))
    word_idx = {w: i for i, (w, _) in enumerate(items)}
    word_idx["<unk>"] = len(word_idx)
    return word_idx


def _real_reader(filename, word_idx, n, data_type, archive):
    def reader():
        with tarfile.open(archive) as tf:
            f = tf.extractfile(filename)
            UNK = word_idx["<unk>"]
            for line in f:
                line = line.decode("utf-8", errors="ignore")
                if DataType.NGRAM == data_type:
                    assert n > -1, "Invalid gram length"
                    toks = ["<s>"] + line.strip().split() + ["<e>"]
                    if len(toks) >= n:
                        ids = [word_idx.get(w, UNK) for w in toks]
                        for i in range(n, len(ids) + 1):
                            yield tuple(ids[i - n:i])
                elif DataType.SEQ == data_type:
                    toks = line.strip().split()
                    ids = [word_idx.get(w, UNK) for w in toks]
                    src = [word_idx["<s>"]] + ids
                    tgt = ids + [word_idx["<e>"]]
                    yield src, tgt
    return reader


def _synth_reader(seed, n_samples, v, n):
    def reader():
        r = np.random.RandomState(seed)
        for _ in range(n_samples):
            start = int(r.randint(0, v - n))
            yield tuple(range(start, start + n))   # learnable successor rule
    return reader


def train(word_idx=None, n=5, data_type=DataType.NGRAM, download=False):
    archive = _archive(download)
    if archive is None:
        v = len(word_idx) if word_idx else VOCAB
        return _synth_reader(30, 3000, v, n)
    word_idx = word_idx or build_dict(download=download)
    return _real_reader(TRAIN_FILE, word_idx, n, data_type, archive)


def test(word_idx=None, n=5, data_type=DataType.NGRAM, download=False):
    archive = _archive(download)
    if archive is None:
        v = len(word_idx) if word_idx else VOCAB
        return _synth_reader(31, 500, v, n)
    word_idx = word_idx or build_dict(download=download)
    return _real_reader(VALID_FILE, word_idx, n, data_type, archive)
