"""CIFAR reader (reference: v2/dataset/cifar.py — tar-of-pickle-batches
parser + md5-cached download).

Real path: the official cifar-10/100-python.tar.gz is parsed straight from
the tar (no extraction), samples normalized to [0,1] [3,32,32] floats.  The
archive is used when already md5-cached under DATA_HOME (or fetched with
``download=True``); otherwise the deterministic synthetic generator keeps
offline CI hermetic."""
from __future__ import annotations

import os
import pickle
import tarfile

from .common import cached_path, synthetic_classification

URL_PREFIX = "https://www.cs.toronto.edu/~kriz/"
CIFAR10_URL = URL_PREFIX + "cifar-10-python.tar.gz"
CIFAR10_MD5 = "c58f30108f718f92721af3b95e74349a"
CIFAR100_URL = URL_PREFIX + "cifar-100-python.tar.gz"
CIFAR100_MD5 = "eb9058c3a382ffc7106e4002c42a8d85"


def _tar_reader(archive_path, sub_name, label_key):
    """Yield (img, label) from every pickle batch whose member name contains
    ``sub_name`` (cifar.py:47 reader_creator)."""
    def reader():
        with tarfile.open(archive_path, mode="r") as tf:
            names = sorted(n for n in tf.getnames() if sub_name in n)
            for name in names:
                batch = pickle.load(tf.extractfile(name), encoding="latin1")
                for x, y in zip(batch["data"], batch[label_key]):
                    yield (x.astype("float32").reshape(3, 32, 32) / 255.0,
                           int(y))
    return reader


def _files_reader(paths, label_key):
    def reader():
        for p in paths:
            with open(p, "rb") as f:
                d = pickle.load(f, encoding="latin1")
            for x, y in zip(d["data"], d[label_key]):
                yield x.astype("float32").reshape(3, 32, 32) / 255.0, int(y)
    return reader


def _make(url, md5, sub_name, label_key, data_dir, do_download, synth_args):
    if data_dir:                       # explicit pre-extracted batches
        if sub_name == "data_batch":
            paths = [os.path.join(data_dir, f"data_batch_{i}")
                     for i in range(1, 6)]
        else:                          # test_batch / cifar-100 train / test
            paths = [os.path.join(data_dir, sub_name)]
        if all(os.path.exists(p) for p in paths):
            return _files_reader(paths, label_key)
    archive = cached_path(url, "cifar", md5, do_download)
    if archive:
        return _tar_reader(archive, sub_name, label_key)
    n, classes, seed, proto = synth_args
    return synthetic_classification(n, (3, 32, 32), classes, seed=seed,
                                    proto_seed=proto)


def train10(data_dir=None, download=False):
    return _make(CIFAR10_URL, CIFAR10_MD5, "data_batch", "labels",
                 data_dir, download, (4000, 10, 10, 9))


def test10(data_dir=None, download=False):
    return _make(CIFAR10_URL, CIFAR10_MD5, "test_batch", "labels",
                 data_dir, download, (800, 10, 11, 9))


def train100(data_dir=None, download=False):
    return _make(CIFAR100_URL, CIFAR100_MD5, "train", "fine_labels",
                 data_dir, download, (4000, 100, 100, 99))


def test100(data_dir=None, download=False):
    return _make(CIFAR100_URL, CIFAR100_MD5, "test", "fine_labels",
                 data_dir, download, (800, 100, 101, 99))
