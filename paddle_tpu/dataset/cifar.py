"""CIFAR reader (reference: v2/dataset/cifar.py; pickle-batch loader +
synthetic fallback)."""
from __future__ import annotations

import os
import pickle

import numpy as np

from .common import synthetic_classification


def _batches_reader(paths, label_key):
    def reader():
        for p in paths:
            with open(p, "rb") as f:
                d = pickle.load(f, encoding="latin1")
            for x, y in zip(d["data"], d[label_key]):
                yield x.astype("float32").reshape(3, 32, 32) / 255.0, int(y)
    return reader


def train10(data_dir=None):
    if data_dir:
        paths = [os.path.join(data_dir, f"data_batch_{i}")
                 for i in range(1, 6)]
        if all(os.path.exists(p) for p in paths):
            return _batches_reader(paths, "labels")
    return synthetic_classification(4000, (3, 32, 32), 10, seed=10,
                                    proto_seed=9)


def test10(data_dir=None):
    if data_dir and os.path.exists(os.path.join(data_dir, "test_batch")):
        return _batches_reader([os.path.join(data_dir, "test_batch")],
                               "labels")
    return synthetic_classification(800, (3, 32, 32), 10, seed=11,
                                    proto_seed=9)


def train100(data_dir=None):
    return synthetic_classification(4000, (3, 32, 32), 100, seed=100,
                                    proto_seed=99)


def test100(data_dir=None):
    return synthetic_classification(800, (3, 32, 32), 100, seed=101,
                                    proto_seed=99)
