"""NLTK movie_reviews sentiment reader (reference: v2/dataset/sentiment.py
— 2000 NLTK movie reviews, pos/neg interleaved, word ids ordered by corpus
frequency, first 1600 train / last 400 test).

The reference shells out to ``nltk.download``; this module parses the
official ``movie_reviews`` corpus layout directly (a zip or directory
containing ``movie_reviews/{pos,neg}/cv*.txt``) so no nltk dependency is
needed.  Offline CI falls back to a deterministic synthetic corpus whose
label is a learnable function of word choice."""
from __future__ import annotations

import os
import re
import zipfile
from itertools import chain

import numpy as np

from .common import DATA_HOME

__all__ = ["train", "test", "get_word_dict",
           "NUM_TRAINING_INSTANCES", "NUM_TOTAL_INSTANCES"]

NUM_TRAINING_INSTANCES = 1600
NUM_TOTAL_INSTANCES = 2000

# NLTK's own tokenizer splits punctuation; \w+ over lowercase text matches
# the reference's ``movie_reviews.words`` closely enough for id assignment.
_TOKEN = re.compile(r"[a-z0-9']+")

_CACHE = {}


def _corpus_location():
    """The movie_reviews corpus under DATA_HOME, as either
    ``corpora/movie_reviews.zip`` (what nltk.download leaves) or an
    extracted ``movie_reviews/`` directory; None when absent."""
    for rel in ("corpora/movie_reviews.zip", "movie_reviews.zip"):
        p = os.path.join(DATA_HOME, rel)
        if os.path.exists(p):
            return p
    for rel in ("corpora/movie_reviews", "movie_reviews"):
        p = os.path.join(DATA_HOME, rel)
        if os.path.isdir(p):
            return p
    return None


def _read_corpus(location):
    """{(category, fileid): [tokens]} sorted by fileid (cv000..cv999)."""
    docs = {}
    if os.path.isdir(location):
        for cat in ("neg", "pos"):
            d = os.path.join(location, cat)
            for fn in sorted(os.listdir(d)):
                if not fn.endswith(".txt"):
                    continue
                with open(os.path.join(d, fn), errors="ignore") as f:
                    docs[(cat, fn)] = _TOKEN.findall(f.read().lower())
    else:
        with zipfile.ZipFile(location) as z:
            for name in sorted(z.namelist()):
                m = re.match(r".*movie_reviews/(pos|neg)/([^/]+\.txt)$", name)
                if not m:
                    continue
                text = z.read(name).decode("utf-8", errors="ignore")
                docs[(m.group(1), m.group(2))] = _TOKEN.findall(text.lower())
    return docs


def get_word_dict(location=None):
    """[(word, id)] sorted by descending corpus frequency
    (sentiment.py:53 get_word_dict)."""
    location = location or _corpus_location()
    if location is None:
        vocab = 5000
        return [(f"w{i}", i) for i in range(vocab)]
    if ("dict", location) not in _CACHE:
        docs = _read_corpus(location)
        freq = {}
        for toks in docs.values():
            for w in toks:
                freq[w] = freq.get(w, 0) + 1
        items = sorted(freq.items(), key=lambda kv: (-kv[1], kv[0]))
        _CACHE[("dict", location)] = [(w, i) for i, (w, _) in
                                      enumerate(items)]
        _CACHE[("docs", location)] = docs
    return _CACHE[("dict", location)]


def load_sentiment_data(location=None):
    """[(word_ids, 0|1)] with neg/pos files interleaved so train/test both
    see both classes (sentiment.py:74 sort_files + :87)."""
    location = location or _corpus_location()
    if location is None:
        return _synthetic()
    word_ids = dict(get_word_dict(location))
    docs = _CACHE[("docs", location)]
    neg = sorted(k for k in docs if k[0] == "neg")
    pos = sorted(k for k in docs if k[0] == "pos")
    out = []
    for key in chain.from_iterable(zip(neg, pos)):
        label = 0 if key[0] == "neg" else 1
        out.append(([word_ids[w] for w in docs[key]], label))
    return out


def _synthetic():
    """2000 docs; positive docs draw from even ids, negative from odd, with
    noise — linearly separable by a bag-of-words model."""
    r = np.random.RandomState(42)
    out = []
    for i in range(NUM_TOTAL_INSTANCES):
        label = i % 2          # interleaved like the real corpus
        L = int(r.randint(20, 120))
        base = r.randint(0, 2500, L) * 2 + label     # parity encodes class
        noise = r.randint(0, 5000, max(1, L // 10))
        toks = np.concatenate([base, noise])
        r.shuffle(toks)
        out.append((toks.tolist(), label))
    return out


def train(location=None):
    """Reader over the first 1600 instances (sentiment.py:115)."""
    data = load_sentiment_data(location)

    def reader():
        for words, cat in data[:NUM_TRAINING_INSTANCES]:
            yield words, cat
    return reader


def test(location=None):
    """Reader over the last 400 instances (sentiment.py:123)."""
    data = load_sentiment_data(location)

    def reader():
        for words, cat in data[NUM_TRAINING_INSTANCES:]:
            yield words, cat
    return reader
