"""Pascal VOC2012 segmentation reader (reference: v2/dataset/voc2012.py —
VOCtrainval tar; splits from ImageSets/Segmentation/{trainval,train,val}.txt;
yields (HWC uint8 image, HW uint8 class mask) pairs, mask values 0-20 +
255 void).

Real path streams JPEG/PNG pairs out of the tar with PIL.  Offline CI uses
deterministic synthetic scenes (rectangles of distinct classes on a
background), same contract, which also feed the SSD detection demo."""
from __future__ import annotations

import io

import numpy as np

from .common import cached_path

__all__ = ["train", "test", "val", "NUM_CLASSES"]

VOC_URL = ("http://host.robots.ox.ac.uk/pascal/VOC/voc2012/"
           "VOCtrainval_11-May-2012.tar")
VOC_MD5 = "6cd6e144f989b92b3379bac3b3de84fd"
SET_FILE = "VOCdevkit/VOC2012/ImageSets/Segmentation/{}.txt"
DATA_FILE = "VOCdevkit/VOC2012/JPEGImages/{}.jpg"
LABEL_FILE = "VOCdevkit/VOC2012/SegmentationClass/{}.png"

NUM_CLASSES = 21            # 20 object classes + background


def _tar_reader(filename, sub_name):
    """(image HWC, mask HW) for every id in the split file
    (voc2012.py:42 reader_creator)."""
    import tarfile

    from PIL import Image

    def reader():
        with tarfile.open(filename) as tar:
            name2mem = {m.name: m for m in tar.getmembers()}
            sets = tar.extractfile(name2mem[SET_FILE.format(sub_name)])
            for line in sets:
                key = line.decode().strip()
                data = tar.extractfile(name2mem[DATA_FILE.format(key)]).read()
                label = tar.extractfile(
                    name2mem[LABEL_FILE.format(key)]).read()
                img = np.array(Image.open(io.BytesIO(data)).convert("RGB"))
                mask = np.array(Image.open(io.BytesIO(label)))
                yield img, mask
    return reader


def _synthetic(n, seed, size=96):
    """Scenes of 1-3 axis-aligned rectangles, each a distinct class painted
    into both the image (as a color block) and the mask — segmentable AND
    detectable, so the same generator feeds the SSD demo via
    ``boxes_from_mask``."""
    def reader():
        r = np.random.RandomState(seed)
        for _ in range(n):
            img = (r.rand(size, size, 3) * 40).astype("uint8")
            mask = np.zeros((size, size), dtype="uint8")
            for _ in range(int(r.randint(1, 4))):
                cls = int(r.randint(1, NUM_CLASSES))
                h = int(r.randint(size // 6, size // 2))
                w = int(r.randint(size // 6, size // 2))
                top = int(r.randint(0, size - h))
                left = int(r.randint(0, size - w))
                color = np.array([cls * 11 % 256, cls * 37 % 256,
                                  cls * 73 % 256], dtype="uint8")
                img[top:top + h, left:left + w] = color
                mask[top:top + h, left:left + w] = cls
            yield img, mask
    return reader


def boxes_from_mask(mask):
    """[(class, ymin, xmin, ymax, xmax)] per connected class region —
    bridges the segmentation masks to the detection demo (the reference
    feeds VOC to SSD through xml annotations; the mask carries the same
    geometry for the classes present)."""
    out = []
    for cls in np.unique(mask):
        if cls in (0, 255):
            continue
        ys, xs = np.nonzero(mask == cls)
        out.append((int(cls), int(ys.min()), int(xs.min()),
                    int(ys.max()) + 1, int(xs.max()) + 1))
    return out


def _make(sub_name, synth, download):
    path = cached_path(VOC_URL, "voc2012", VOC_MD5, download)
    if path:
        return _tar_reader(path, sub_name)
    n, seed = synth
    return _synthetic(n, seed)


def train(download=False):
    """trainval split, 2913 images (voc2012.py:67)."""
    return _make("trainval", (200, 30), download)


def test(download=False):
    """train split, 1464 images (voc2012.py:74)."""
    return _make("train", (60, 31), download)


def val(download=False):
    """val split, 1449 images (voc2012.py:81)."""
    return _make("val", (60, 32), download)
