"""LETOR MQ2007 learning-to-rank reader (reference: v2/dataset/mq2007.py —
``label qid:N 1:v .. 46:v # comment`` lines grouped per query; pointwise /
pairwise / listwise / plain_txt generators; Fold1 train/test).

The reference needs ``rarfile`` to unpack MQ2007.rar; this module parses
the extracted text files directly (point it at the file or drop the
extracted ``MQ2007/`` tree under DATA_HOME), and offline CI uses a
deterministic synthetic corpus whose relevance is a noisy linear function
of the features — genuinely learnable by rank_cost/lambda-rank models."""
from __future__ import annotations

import functools
import os
import random

import numpy as np

from .common import DATA_HOME

__all__ = ["train", "test", "Query", "QueryList", "gen_point", "gen_pair",
           "gen_list", "gen_plain_txt", "query_filter", "load_from_text",
           "FEATURE_DIM"]

FEATURE_DIM = 46


class Query:
    """One query-document pair: relevance score, query id, 46 features,
    trailing comment (mq2007.py:49)."""

    def __init__(self, query_id=-1, relevance_score=-1, feature_vector=None,
                 description=""):
        self.query_id = query_id
        self.relevance_score = relevance_score
        self.feature_vector = feature_vector or []
        self.description = description

    def __str__(self):
        return "%s %s %s" % (self.relevance_score, self.query_id,
                             " ".join(str(f) for f in self.feature_vector))

    @classmethod
    def parse(cls, text):
        """``label qid:N 1:v ... 46:v # docid`` → Query, or None on a
        malformed line (mq2007.py:84)."""
        comment_pos = text.find("#")
        desc = text[comment_pos + 1:].strip() if comment_pos >= 0 else ""
        line = text[:comment_pos] if comment_pos >= 0 else text
        parts = line.split()
        if len(parts) != FEATURE_DIM + 2:
            return None
        q = cls(description=desc)
        q.relevance_score = int(parts[0])
        q.query_id = int(parts[1].split(":")[1])
        q.feature_vector = [float(p.split(":")[1]) for p in parts[2:]]
        return q


class QueryList:
    """All documents of one query (mq2007.py:105)."""

    def __init__(self, querylist=None):
        self.query_id = -1
        self.querylist = []
        for q in querylist or []:
            self._add_query(q)

    def __iter__(self):
        return iter(self.querylist)

    def __len__(self):
        return len(self.querylist)

    def __getitem__(self, i):
        return self.querylist[i]

    def _correct_ranking_(self):
        self.querylist.sort(key=lambda x: x.relevance_score, reverse=True)

    def _add_query(self, query):
        if self.query_id == -1:
            self.query_id = query.query_id
        elif self.query_id != query.query_id:
            raise ValueError("query in list must be same query_id")
        self.querylist.append(query)


def _as_querylist(querylist):
    ql = (querylist if isinstance(querylist, QueryList)
          else QueryList(querylist))
    ql._correct_ranking_()
    return ql


def gen_plain_txt(querylist):
    """(query_id, label, features) per doc (mq2007.py:147)."""
    ql = _as_querylist(querylist)
    for q in ql:
        yield ql.query_id, q.relevance_score, np.array(q.feature_vector)


def gen_point(querylist):
    """(label, features) per doc — pointwise LTR (mq2007.py:168)."""
    for q in _as_querylist(querylist):
        yield q.relevance_score, np.array(q.feature_vector)


def gen_pair(querylist, partial_order="full"):
    """(1, better_features, worse_features) per ordered doc pair — the
    rank_cost training signal (mq2007.py:187)."""
    ql = _as_querylist(querylist)
    for i in range(len(ql)):
        for j in range(i + 1, len(ql)):
            a, b = ql[i], ql[j]
            if a.relevance_score > b.relevance_score:
                hi, lo = a, b
            elif a.relevance_score < b.relevance_score:
                hi, lo = b, a
            else:
                continue
            yield (np.array([1]), np.array(hi.feature_vector),
                   np.array(lo.feature_vector))


def gen_list(querylist):
    """([labels], [features]) whole-query — listwise LTR (mq2007.py:230)."""
    ql = _as_querylist(querylist)
    yield (np.array([[q.relevance_score] for q in ql]),
           np.array([q.feature_vector for q in ql]))


def query_filter(querylists):
    """Drop queries with no relevant documents (mq2007.py:250)."""
    return [ql for ql in querylists
            if sum(q.relevance_score for q in ql) != 0]


def load_from_text(filepath, shuffle=True):
    """Parse a LETOR text file into QueryLists (mq2007.py:268)."""
    querylists, current, prev_id = [], None, None
    with open(filepath) as f:
        for line in f:
            q = Query.parse(line)
            if q is None:
                continue
            if q.query_id != prev_id:
                if current is not None:
                    querylists.append(current)
                current, prev_id = QueryList(), q.query_id
            current._add_query(q)
    if current is not None:
        querylists.append(current)
    if shuffle:
        random.shuffle(querylists)
    return querylists


def _synthetic_querylists(n_queries, seed):
    """Relevance = quantized noisy linear score of the features, so a
    linear ranker can beat random and pairwise training converges."""
    r = np.random.RandomState(seed)
    w = np.random.RandomState(2007).randn(FEATURE_DIM)
    out = []
    for qid in range(1, n_queries + 1):
        ql = QueryList()
        for _ in range(int(r.randint(8, 24))):
            feat = r.rand(FEATURE_DIM)
            score = feat @ w + 0.3 * r.randn()
            rel = int(np.clip(np.floor((score + 2.0) / 1.5), 0, 2))
            ql._add_query(Query(qid, rel, feat.tolist(), "synthetic"))
        out.append(ql)
    return out


def _resolve(filepath):
    """The extracted LETOR text file under DATA_HOME, or None."""
    for root in (os.path.join(DATA_HOME, "MQ2007"), DATA_HOME):
        p = os.path.join(root, filepath)
        if os.path.exists(p):
            return p
    return None


def _reader(filepath, format="pairwise", shuffle=True, synth_seed=0):
    """Reader over one fold file in the requested LTR format
    (mq2007.py:295)."""
    def reader():
        path = _resolve(filepath)
        if path is not None:
            querylists = query_filter(load_from_text(path, shuffle=shuffle))
        else:
            querylists = query_filter(
                _synthetic_querylists(120, seed=synth_seed))
        for ql in querylists:
            if format == "plain_txt":
                yield next(gen_plain_txt(ql))
            elif format == "pointwise":
                yield next(gen_point(ql))
            elif format == "pairwise":
                yield from gen_pair(ql)
            elif format == "listwise":
                yield from gen_list(ql)
            else:
                raise ValueError(f"unknown format {format!r}")
    return reader


train = functools.partial(_reader, filepath="MQ2007/Fold1/train.txt",
                          synth_seed=50)
test = functools.partial(_reader, filepath="MQ2007/Fold1/test.txt",
                         synth_seed=51)
