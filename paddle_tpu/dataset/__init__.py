"""Dataset readers (reference: python/paddle/v2/dataset/ — mnist, cifar,
imdb, imikolov, movielens, uci_housing, conll05, wmt14, sentiment...).

The reference downloads real corpora at import time; this environment has no
egress, so each module provides (a) loaders for locally-present files in the
reference formats when a path is given and (b) deterministic synthetic
generators with the same reader protocol and shapes, so every demo/benchmark
script runs unchanged.  Swap in real data by pointing the loader at files.
"""
from . import (mnist, cifar, imdb, imikolov, movielens, uci_housing,
               conll05, wmt14, sentiment, flowers, voc2012, mq2007)

__all__ = ["mnist", "cifar", "imdb", "imikolov", "movielens", "uci_housing",
           "conll05", "wmt14", "sentiment", "flowers", "voc2012", "mq2007"]
