"""Oxford 102 Flowers reader (reference: v2/dataset/flowers.py —
102flowers.tgz of JPEGs + imagelabels.mat + setid.mat; train/test splits
deliberately swapped (tstid is the larger split, used for training);
samples are flattened float32 CHW crops + 0-based label).

Real path decodes JPEGs straight out of the tar with PIL and applies the
reference transform (resize shorter side to 256, center/random crop 224,
channel-mean subtract, CHW).  Offline CI uses a deterministic synthetic
generator with the same sample contract."""
from __future__ import annotations


import tarfile

import numpy as np

from .common import cached_path

__all__ = ["train", "test", "valid"]

DATA_URL = "http://www.robots.ox.ac.uk/~vgg/data/flowers/102/102flowers.tgz"
LABEL_URL = ("http://www.robots.ox.ac.uk/~vgg/data/flowers/102/"
             "imagelabels.mat")
SETID_URL = "http://www.robots.ox.ac.uk/~vgg/data/flowers/102/setid.mat"
DATA_MD5 = "33bfc11892f1e405ca193ae9a9f2a118"
LABEL_MD5 = "e0620be6f572b9609742df49c70aed4d"
SETID_MD5 = "a5357ecc9cb78c4bef273ce3793fc85c"

# Reference swaps the official splits: tstid (6149 imgs) trains, trnid
# (1020) tests (flowers.py:50-55).
TRAIN_FLAG = "tstid"
TEST_FLAG = "trnid"
VALID_FLAG = "valid"

MEAN = np.array([103.94, 116.78, 123.68], dtype="float32")  # BGR means
NUM_CLASSES = 102
CROP = 224


def default_mapper(is_train, sample):
    """(jpeg_bytes, label) → (flat float32 CHW crop, label)
    (flowers.py:58) — the reference transform via paddle_tpu.image
    (BGR decode, short-side resize, crop/flip, CHW, mean subtract)."""
    from .. import image

    data, label = sample
    img = image.load_image_bytes(data)
    img = image.simple_transform(img, 256, CROP, is_train, mean=MEAN)
    return np.ascontiguousarray(img).reshape(-1), label


def _loadmat_indices(path, key):
    import scipy.io as scio
    return scio.loadmat(path)[key][0]


def _tar_reader(data_file, label_file, setid_file, flag, mapper):
    """Stream (mapped_image, 0-based label) for the split's image ids
    (flowers.py:73 reader_creator, without the batch-file detour — the tar
    is indexed once and streamed)."""
    labels = _loadmat_indices(label_file, "labels")
    indexes = _loadmat_indices(setid_file, flag)

    def reader():
        with tarfile.open(data_file) as tf:
            members = {m.name: m for m in tf.getmembers()}
            for i in indexes:
                name = "jpg/image_%05d.jpg" % i
                raw = tf.extractfile(members[name]).read()
                yield mapper((raw, int(labels[i - 1]) - 1))
    return reader


def _synthetic(n, seed, is_train):
    """Class-k images tile a fixed low-res prototype (kept small so the
    generator is cheap), matching the real sample contract: flat float32
    of length 3*224*224 and a label in [0, 102)."""
    r_protos = np.random.RandomState(7)
    protos = r_protos.rand(NUM_CLASSES, 3, 8, 8).astype("float32") * 60.0

    def reader():
        r = np.random.RandomState(seed)
        for _ in range(n):
            y = int(r.randint(NUM_CLASSES))
            img = np.kron(protos[y], np.ones((1, CROP // 8, CROP // 8),
                                             dtype="float32"))
            img += 5.0 * r.randn(3, CROP, CROP).astype("float32")
            yield img.reshape(-1), y
    return reader


def _make(flag, mapper, is_train, synth, download):
    data = cached_path(DATA_URL, "flowers", DATA_MD5, download)
    label = cached_path(LABEL_URL, "flowers", LABEL_MD5, download)
    setid = cached_path(SETID_URL, "flowers", SETID_MD5, download)
    if data and label and setid:
        return _tar_reader(data, label, setid, flag, mapper)
    n, seed = synth
    return _synthetic(n, seed, is_train)


def train(mapper=None, download=False):
    """Training reader: 6149 images (official tstid) (flowers.py:127)."""
    import functools
    mapper = mapper or functools.partial(default_mapper, True)
    return _make(TRAIN_FLAG, mapper, True, (600, 20), download)


def test(mapper=None, download=False):
    """Test reader: 1020 images (official trnid) (flowers.py:150)."""
    import functools
    mapper = mapper or functools.partial(default_mapper, False)
    return _make(TEST_FLAG, mapper, False, (120, 21), download)


def valid(mapper=None, download=False):
    """Validation reader: 1020 images (flowers.py:173)."""
    import functools
    mapper = mapper or functools.partial(default_mapper, False)
    return _make(VALID_FLAG, mapper, False, (120, 22), download)
