"""MNIST reader (reference: v2/dataset/mnist.py — idx-format parser +
reader protocol; synthetic fallback when files are absent)."""
from __future__ import annotations

import gzip
import os
import struct

import numpy as np

from .common import synthetic_classification

TRAIN_N, TEST_N = 8000, 1000


def _idx_images(path):
    op = gzip.open if path.endswith(".gz") else open
    with op(path, "rb") as f:
        magic, n, rows, cols = struct.unpack(">IIII", f.read(16))
        data = np.frombuffer(f.read(), np.uint8).reshape(n, rows * cols)
        # v2 normalization (reference mnist.py:66): pixels in [-1, 1]
        return data.astype("float32") / 255.0 * 2.0 - 1.0


def _idx_labels(path):
    op = gzip.open if path.endswith(".gz") else open
    with op(path, "rb") as f:
        magic, n = struct.unpack(">II", f.read(8))
        return np.frombuffer(f.read(), np.uint8).astype("int64")


def reader_from_files(image_path, label_path):
    imgs, labs = _idx_images(image_path), _idx_labels(label_path)

    def reader():
        for x, y in zip(imgs, labs):
            yield x, int(y)
    return reader


def train(data_dir=None):
    if data_dir and os.path.exists(os.path.join(
            data_dir, "train-images-idx3-ubyte.gz")):
        return reader_from_files(
            os.path.join(data_dir, "train-images-idx3-ubyte.gz"),
            os.path.join(data_dir, "train-labels-idx1-ubyte.gz"))
    return synthetic_classification(TRAIN_N, (784,), 10, seed=90051,
                                    proto_seed=90050)


def test(data_dir=None):
    if data_dir and os.path.exists(os.path.join(
            data_dir, "t10k-images-idx3-ubyte.gz")):
        return reader_from_files(
            os.path.join(data_dir, "t10k-images-idx3-ubyte.gz"),
            os.path.join(data_dir, "t10k-labels-idx1-ubyte.gz"))
    return synthetic_classification(TEST_N, (784,), 10, seed=90052,
                                    proto_seed=90050)
