"""MovieLens 1-M reader (reference: v2/dataset/movielens.py — ml-1m.zip
parser with MovieInfo/UserInfo metadata, title/category dictionaries, and
the 90/10 rating split; synthetic fallback for offline CI)."""
from __future__ import annotations

import os
import re
import zipfile

import numpy as np

from .common import cached_path

URL = "http://files.grouplens.org/datasets/movielens/ml-1m.zip"
MD5 = "c4d9eecfca2ab87c1945afe126590906"

age_table = [1, 18, 25, 35, 45, 50, 56]

NUM_USERS, NUM_MOVIES = 944, 1683          # synthetic-mode id spaces


class MovieInfo:
    """Movie id, title and categories (movielens.py:44)."""

    def __init__(self, index, categories, title):
        self.index = int(index)
        self.categories = categories
        self.title = title

    def value(self):
        return [self.index,
                [CATEGORIES_DICT[c] for c in self.categories],
                [MOVIE_TITLE_DICT[w.lower()] for w in self.title.split()]]


class UserInfo:
    """User id, gender, age bucket, job (movielens.py:71)."""

    def __init__(self, index, gender, age, job_id):
        self.index = int(index)
        self.is_male = gender == "M"
        self.age = age_table.index(int(age))
        self.job_id = int(job_id)

    def value(self):
        return [self.index, 0 if self.is_male else 1, self.age, self.job_id]


MOVIE_INFO = None
MOVIE_TITLE_DICT = None
CATEGORIES_DICT = None
USER_INFO = None
_META_ARCHIVE = None      # which archive the globals were parsed from


def _archive(do_download=False):
    return cached_path(URL, "movielens", MD5, do_download)


def _init_meta(fn):
    global MOVIE_INFO, MOVIE_TITLE_DICT, CATEGORIES_DICT, USER_INFO, \
        _META_ARCHIVE
    if MOVIE_INFO is not None and _META_ARCHIVE == fn:
        return
    _META_ARCHIVE = fn
    MOVIE_INFO = None
    pattern = re.compile(r"^(.*)\((\d+)\)$")
    MOVIE_INFO, title_words, categories = {}, set(), set()
    with zipfile.ZipFile(fn) as package:
        with package.open("ml-1m/movies.dat") as f:
            for line in f:
                mid, title, cats = line.decode(
                    "latin1").strip().split("::")
                cats = cats.split("|")
                categories.update(cats)
                title = pattern.match(title).group(1)
                MOVIE_INFO[int(mid)] = MovieInfo(mid, cats, title)
                title_words.update(w.lower() for w in title.split())
        MOVIE_TITLE_DICT = {w: i for i, w in enumerate(sorted(title_words))}
        CATEGORIES_DICT = {c: i for i, c in enumerate(sorted(categories))}
        USER_INFO = {}
        with package.open("ml-1m/users.dat") as f:
            for line in f:
                uid, gender, age, job, _ = line.decode(
                    "latin1").strip().split("::")
                USER_INFO[int(uid)] = UserInfo(uid, gender, age, job)


def _real_reader(archive, is_test, test_ratio=0.1, rand_seed=0):
    """Rating rows -> user.value() + movie.value() + [score]
    (movielens.py:141 __reader__); the split is a seeded per-row coin flip
    like the reference."""
    def reader():
        _init_meta(archive)
        rng = np.random.RandomState(rand_seed)
        with zipfile.ZipFile(archive) as package:
            with package.open("ml-1m/ratings.dat") as f:
                for line in f:
                    if (rng.rand() < test_ratio) != is_test:
                        continue
                    uid, mid, score, _ = line.decode(
                        "latin1").strip().split("::")
                    usr = USER_INFO[int(uid)]
                    mov = MOVIE_INFO[int(mid)]
                    yield usr.value() + mov.value() + [[float(score)]]
    return reader


def _ratings(seed, n):
    def reader():
        r = np.random.RandomState(seed)
        for _ in range(n):
            u = int(r.randint(NUM_USERS))
            m = int(r.randint(NUM_MOVIES))
            score = float((u + m) % 5 + 1)       # learnable structure
            yield u, m, score
    return reader


def max_user_id(download=False):
    archive = _archive(download)
    if archive is None:
        return NUM_USERS - 1
    _init_meta(archive)
    return max(USER_INFO)


def max_movie_id(download=False):
    archive = _archive(download)
    if archive is None:
        return NUM_MOVIES - 1
    _init_meta(archive)
    return max(MOVIE_INFO)


def max_job_id(download=False):
    archive = _archive(download)
    if archive is None:
        return 20
    _init_meta(archive)
    return max(u.job_id for u in USER_INFO.values())


def get_movie_title_dict(download=False):
    archive = _archive(download)
    if archive is None:
        return {}
    _init_meta(archive)
    return MOVIE_TITLE_DICT


def movie_categories(download=False):
    archive = _archive(download)
    if archive is None:
        return {}
    _init_meta(archive)
    return CATEGORIES_DICT


def train(download=False):
    archive = _archive(download)
    if archive is None:
        return _ratings(40, 4000)
    return _real_reader(archive, is_test=False)


def test(download=False):
    archive = _archive(download)
    if archive is None:
        return _ratings(41, 800)
    return _real_reader(archive, is_test=True)
