"""MovieLens reader (reference: v2/dataset/movielens.py; synthetic)."""
from __future__ import annotations

import numpy as np

NUM_USERS, NUM_MOVIES = 944, 1683


def max_user_id():
    return NUM_USERS - 1


def max_movie_id():
    return NUM_MOVIES - 1


def _ratings(seed, n):
    def reader():
        r = np.random.RandomState(seed)
        for _ in range(n):
            u = int(r.randint(NUM_USERS))
            m = int(r.randint(NUM_MOVIES))
            score = float((u + m) % 5 + 1)       # learnable structure
            yield u, m, score
    return reader


def train():
    return _ratings(40, 4000)


def test():
    return _ratings(41, 800)
