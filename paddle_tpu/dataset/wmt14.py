"""WMT14 EN→FR machine-translation reader (reference:
v2/dataset/wmt14.py — shrunk wmt14.tgz with src.dict/trg.dict + tab-
separated parallel train/test/gen files; samples are (src_ids, trg_ids,
trg_ids_next) with <s>/<e> framing and UNK_IDX=2, sequences >80 tokens
dropped).

Offline CI uses a deterministic synthetic parallel corpus whose target is
a learnable function of the source (reversal in a shifted vocab), so the
book-style NMT test trains and beam-decodes hermetically; the real archive
parses when the cache holds it (``download=True``)."""
from __future__ import annotations

import tarfile

import numpy as np

from .common import cached_path

__all__ = ["train", "test", "gen", "build_dict", "get_dict"]

URL_TRAIN = ("http://paddlepaddle.cdn.bcebos.com/demo/wmt_shrinked_data/"
             "wmt14.tgz")
MD5_TRAIN = "0791583d57d5beb693b9414c5b36798c"

START = "<s>"
END = "<e>"
UNK = "<unk>"
UNK_IDX = 2
MAX_LEN = 80

_DICT_MEMO = {}


def _archive(do_download=False):
    return cached_path(URL_TRAIN, "wmt14", MD5_TRAIN, do_download)


def _read_dicts(tar_path, dict_size):
    """First ``dict_size`` lines of src.dict / trg.dict (wmt14.py:45)."""
    key = (tar_path, dict_size)
    if key in _DICT_MEMO:
        return _DICT_MEMO[key]

    def to_dict(fd, size):
        out = {}
        for i, line in enumerate(fd):
            if i >= size:
                break
            out[line.strip().decode("utf-8", errors="ignore")] = i
        return out

    with tarfile.open(tar_path, mode="r") as f:
        src_name = [m.name for m in f if m.name.endswith("src.dict")]
        trg_name = [m.name for m in f if m.name.endswith("trg.dict")]
        assert len(src_name) == 1 and len(trg_name) == 1
        src = to_dict(f.extractfile(src_name[0]), dict_size)
        trg = to_dict(f.extractfile(trg_name[0]), dict_size)
    _DICT_MEMO[key] = (src, trg)
    return src, trg


def _tar_reader(tar_path, file_name, dict_size):
    """Yield (src_ids, trg_ids, trg_ids_next) from the tab-separated
    parallel file (wmt14.py:71): source framed <s>...<e>, target input
    <s>-prefixed, target label <e>-suffixed, >80-token pairs dropped."""
    def reader():
        src_dict, trg_dict = _read_dicts(tar_path, dict_size)
        with tarfile.open(tar_path, mode="r") as f:
            names = [m.name for m in f if m.name.endswith(file_name)]
            for name in names:
                for line in f.extractfile(name):
                    parts = line.decode(
                        "utf-8", errors="ignore").strip().split("\t")
                    if len(parts) != 2:
                        continue
                    src_ids = [src_dict.get(w, UNK_IDX) for w in
                               [START] + parts[0].split() + [END]]
                    trg_words = parts[1].split()
                    trg_ids = [trg_dict.get(w, UNK_IDX) for w in trg_words]
                    if len(src_ids) > MAX_LEN or len(trg_ids) > MAX_LEN:
                        continue
                    trg_next = trg_ids + [trg_dict[END]]
                    trg_ids = [trg_dict[START]] + trg_ids
                    yield src_ids, trg_ids, trg_next
    return reader


def _synthetic_parallel(n, dict_size, seed):
    """Deterministic offline corpus: target = reversed source shifted by
    +3 in the shared id space — a real (if easy) translation function, so
    training cost falls and beam decode can be scored against the known
    mapping."""
    def reader():
        r = np.random.RandomState(seed)
        start, end = 0, 1
        for _ in range(n):
            L = int(r.randint(3, 9))
            body = r.randint(3, dict_size - 3, L).tolist()
            src = [start] + body + [end]
            trg_body = [(t + 3) % (dict_size - 3) + 3
                        for t in reversed(body)]
            yield src, [start] + trg_body, trg_body + [end]
    return reader


def train(dict_size, download=False):
    """Training reader: (src_ids, trg_ids, trg_ids_next) (wmt14.py:105)."""
    path = _archive(download)
    if path is None:
        return _synthetic_parallel(2000, dict_size, seed=140)
    return _tar_reader(path, "train/train", dict_size)


def test(dict_size, download=False):
    path = _archive(download)
    if path is None:
        return _synthetic_parallel(200, dict_size, seed=141)
    return _tar_reader(path, "test/test", dict_size)


def gen(dict_size, download=False):
    """Generation split (wmt14.py:136)."""
    path = _archive(download)
    if path is None:
        return _synthetic_parallel(50, dict_size, seed=142)
    return _tar_reader(path, "gen/gen", dict_size)


def build_dict(dict_size, download=False):
    """(src_dict, trg_dict) word→id (first dict_size entries)."""
    path = _archive(download)
    if path is None:
        d = {START: 0, END: 1, UNK: 2}
        d.update({f"w{i}": i for i in range(3, dict_size)})
        return dict(d), dict(d)
    return _read_dicts(path, dict_size)


def get_dict(dict_size, reverse=True, download=False):
    """id→word (or word→id with reverse=False) pair (wmt14.py:149)."""
    src, trg = build_dict(dict_size, download)
    if reverse:
        src = {v: k for k, v in src.items()}
        trg = {v: k for k, v in trg.items()}
    return src, trg
