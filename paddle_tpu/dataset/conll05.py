"""CoNLL-05 SRL reader (reference: v2/dataset/conll05.py; synthetic
tagged sequences)."""
from __future__ import annotations

import numpy as np

WORD_VOCAB, NUM_TAGS = 1000, 9


def get_dict():
    word_dict = {f"w{i}": i for i in range(WORD_VOCAB)}
    verb_dict = {f"v{i}": i for i in range(50)}
    label_dict = {f"t{i}": i for i in range(NUM_TAGS)}
    return word_dict, verb_dict, label_dict


def _gen(seed, n):
    def reader():
        r = np.random.RandomState(seed)
        for _ in range(n):
            L = int(r.randint(5, 25))
            words = r.randint(0, WORD_VOCAB, L).tolist()
            tags = [w % NUM_TAGS for w in words]      # learnable tagging
            yield words, tags
    return reader


def train():
    return _gen(60, 1000)


def test():
    return _gen(61, 200)
