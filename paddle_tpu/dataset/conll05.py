"""CoNLL-2005 SRL dataset (reference: python/paddle/v2/dataset/conll05.py).

Official format: the public ``conll05st-tests.tar.gz`` carries parallel
line streams ``test.wsj.words.gz`` (one token per line, blank line ends a
sentence) and ``test.wsj.props.gz`` (per line: the target verb column
followed by one bracket-tagged column per predicate).  Parsing converts
each predicate's bracket column — ``(A0*``, ``*``, ``*)`` — into a BIO
tag sequence and emits one (sentence, predicate, BIO labels) item per
predicate, then the reader expands each item into the 9-slot SRL feature
tuple (words, 5 predicate-context columns, predicate id, region mark,
labels) the book demo trains on.

Offline (no cached archive) ``train``/``test`` fall back to synthetic
learnable sequences so hermetic tests run; the real-format parsing paths
(`corpus_reader`, `reader_creator`) are exercised against a synthesized
official-layout tarball in tests/test_dataset_tail.py.
"""
from __future__ import annotations

import gzip
import tarfile

import numpy as np

DATA_URL = "http://www.cs.upc.edu/~srlconll/conll05st-tests.tar.gz"
DATA_MD5 = "387719152ae52d60422c016e92a742fc"
WORDS_NAME = "conll05st-release/test.wsj/words/test.wsj.words.gz"
PROPS_NAME = "conll05st-release/test.wsj/props/test.wsj.props.gz"

# The published dictionaries the reference trains/embeds against
# (reference conll05.py:33-40) — one token per line, line index == id.
WORDDICT_URL = "http://paddlemodels.bj.bcebos.com/conll05st%2FwordDict.txt"
WORDDICT_MD5 = "ea7fb7d4c75cc6254716f0177a506baa"
VERBDICT_URL = "http://paddlemodels.bj.bcebos.com/conll05st%2FverbDict.txt"
VERBDICT_MD5 = "0d2977293bbb6cbefab5b0f97db1e77c"
TRGDICT_URL = "http://paddlemodels.bj.bcebos.com/conll05st%2FtargetDict.txt"
TRGDICT_MD5 = "d8c7f03ceb5fc2e5a0fa7503a4353751"

UNK_IDX = 0
WORD_VOCAB, NUM_TAGS = 1000, 9


def load_dict(filename):
    """One entry per line -> {token: line_index} (the dict-file format of
    the published wordDict/verbDict/targetDict)."""
    d = {}
    with open(filename) as f:
        for i, line in enumerate(f):
            d[line.strip()] = i
    return d


def _bracket_to_bio(column):
    """One predicate's bracket column -> BIO tags.  ``(TAG*`` opens TAG
    (multi-token until ``*)``), ``(TAG*)`` is a single-token span, bare
    ``*`` is O outside spans / I-TAG inside."""
    tags = []
    cur, inside = "O", False
    for tok in column:
        if tok == "*":
            tags.append("I-" + cur if inside else "O")
        elif tok == "*)":
            tags.append("I-" + cur)
            inside = False
        elif "(" in tok and ")" in tok:
            cur = tok[1:tok.index("*")]
            tags.append("B-" + cur)
            inside = False
        elif "(" in tok:
            cur = tok[1:tok.index("*")]
            tags.append("B-" + cur)
            inside = True
        else:
            raise RuntimeError(f"unexpected SRL bracket label {tok!r}")
    return tags


def corpus_reader(data_path, words_name=WORDS_NAME, props_name=PROPS_NAME):
    """Iterate (sentence words, predicate, BIO labels) triples from an
    official-layout archive — one triple per predicate column."""

    def flush(words, cols):
        verbs = [row[0] for row in cols if row[0] != "-"]
        n_preds = len(cols[0]) - 1 if cols else 0
        for p in range(n_preds):
            col = [c[p + 1] for c in cols]
            yield list(words), verbs[p], _bracket_to_bio(col)

    def reader():
        with tarfile.open(data_path) as tf:
            with gzip.GzipFile(fileobj=tf.extractfile(words_name)) as wf, \
                    gzip.GzipFile(fileobj=tf.extractfile(props_name)) as pf:
                words, cols = [], []
                for wline, pline in zip(wf, pf):
                    word = wline.decode().strip()
                    fields = pline.decode().split()
                    if not fields:                     # sentence boundary
                        if words:
                            yield from flush(words, cols)
                        words, cols = [], []
                    else:
                        words.append(word)
                        cols.append(fields)
                if words:       # no trailing blank line: flush the tail
                    yield from flush(words, cols)

    return reader


def reader_creator(corpus_reader, word_dict, predicate_dict, label_dict):
    """Expand each (sentence, predicate, labels) into the 9-slot SRL
    feature tuple: word ids, ctx_n2/n1/0/p1/p2 predicate-window columns
    (broadcast over the sentence), predicate id, +/-2-window region mark,
    label ids (reference conll05.py:127-178 semantics)."""

    def reader():
        for sentence, predicate, labels in corpus_reader():
            n = len(sentence)
            v = labels.index("B-V")
            mark = [0] * n
            ctx = {}
            for off, key, pad in ((-2, "n2", "bos"), (-1, "n1", "bos"),
                                  (0, "0", None), (1, "p1", "eos"),
                                  (2, "p2", "eos")):
                i = v + off
                if 0 <= i < n:
                    mark[i] = 1
                    ctx[key] = sentence[i]
                else:
                    ctx[key] = pad
            word_idx = [word_dict.get(w, UNK_IDX) for w in sentence]
            bcast = {k: [word_dict.get(w, UNK_IDX)] * n
                     for k, w in ctx.items()}
            pred_idx = [predicate_dict.get(predicate, UNK_IDX)] * n
            label_idx = [label_dict[t] for t in labels]
            yield (word_idx, bcast["n2"], bcast["n1"], bcast["0"],
                   bcast["p1"], bcast["p2"], pred_idx, mark, label_idx)

    return reader


def _archive(download=False):
    """md5-verified official archive via the shared cache probe (a
    populated cache must not silently change what a DEFAULT reader
    yields — real data only on explicit request, common.cached_path)."""
    from .common import cached_path
    return cached_path(DATA_URL, "conll05st", DATA_MD5,
                       do_download=download)


def _published_dicts(download=False):
    """The reference's published wordDict/verbDict/targetDict via the
    shared cache probe; (word, verb, label) dicts, or None when any file
    is absent and cannot be fetched."""
    from .common import cached_path
    paths = []
    for url, md5 in ((WORDDICT_URL, WORDDICT_MD5),
                     (VERBDICT_URL, VERBDICT_MD5),
                     (TRGDICT_URL, TRGDICT_MD5)):
        try:
            p = cached_path(url, "conll05st", md5, do_download=download)
        except (RuntimeError, OSError) as e:
            import warnings
            warnings.warn(f"conll05: published dict {url} unavailable "
                          f"({e}); falling back to corpus-derived dicts "
                          f"(token ids will NOT match the reference)")
            return None
        if p is None:
            return None
        paths.append(p)
    return tuple(load_dict(p) for p in paths)


def get_dict(download=False):
    """Word/verb/label dictionaries.

    With ``download=True`` the reference's PUBLISHED wordDict/verbDict/
    targetDict files are loaded via :func:`load_dict` (served from the
    shared cache when already present — no re-fetch), so token ids match
    the reference exactly — the id assignment its pretrained SRL
    embedding (the ``get_embedding`` workflow) and any model trained
    against the published vocabulary expect.  When the published files
    are unavailable but the test
    corpus archive is, the dicts are BUILT FROM THE CORPUS instead:
    alphabetic enumeration of the test split.  Corpus-derived ids are
    **incompatible** with the published ids (different vocabulary,
    different order), so checkpoints/embeddings cannot be exchanged
    between the two modes.  By default (no cache, no download) both fall
    back to the synthetic vocabulary the hermetic tests use."""
    published = _published_dicts(download)
    if published is not None:
        return published
    arch = _archive(download)
    if arch is None:
        word_dict = {f"w{i}": i for i in range(WORD_VOCAB)}
        verb_dict = {f"v{i}": i for i in range(50)}
        label_dict = {f"t{i}": i for i in range(NUM_TAGS)}
        return word_dict, verb_dict, label_dict
    words, verbs, tags = set(), set(), set()
    for sentence, predicate, labels in corpus_reader(arch)():
        words.update(sentence)
        verbs.add(predicate)
        tags.update(labels)
    # reserved ids first: <unk> takes UNK_IDX (0) and the bos/eos boundary
    # paddings get their own entries, so edge-of-sentence context features
    # never alias a real corpus word
    word_dict = {"<unk>": UNK_IDX, "bos": 1, "eos": 2}
    for w in sorted(words - set(word_dict)):
        word_dict[w] = len(word_dict)
    verb_dict = {w: i for i, w in enumerate(sorted(verbs))}
    label_dict = {t: i for i, t in enumerate(sorted(tags))}
    return word_dict, verb_dict, label_dict


def _gen(seed, n):
    """Synthetic learnable tagging fallback (shape-compatible 2-tuples for
    the book test's simplified pipeline)."""

    def reader():
        r = np.random.RandomState(seed)
        for _ in range(n):
            L = int(r.randint(5, 25))
            words = r.randint(0, WORD_VOCAB, L).tolist()
            tags = [w % NUM_TAGS for w in words]      # learnable tagging
            yield words, tags

    return reader


def train():
    return _gen(60, 1000)


def test(download=False):
    """Synthetic 2-tuples by default (matches train()); pass
    ``download=True`` for the official corpus as 9-slot SRL tuples —
    explicit opt-in, because the schemas differ."""
    arch = _archive(download)
    if arch is None:
        return _gen(61, 200)
    word_dict, verb_dict, label_dict = get_dict(download)
    return reader_creator(corpus_reader(arch), word_dict, verb_dict,
                          label_dict)
