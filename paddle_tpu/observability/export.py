"""Export & surfacing: JSONL structured log, merged snapshots, device
memory sampling, periodic reports, and the ``stats`` CLI summarizer.

The JSONL log (flag ``metrics_log`` / env ``PADDLE_TPU_METRICS_LOG``) is
an append-only stream of one-line JSON events::

    {"ts": <unix s>, "kind": "step",     ...per-dispatch telemetry}
    {"ts": <unix s>, "kind": "snapshot", ...metrics_snapshot() payload}
    {"ts": <unix s>, "kind": "nan",      ...NaN-provenance diagnostic}

``python -m paddle_tpu stats run.jsonl`` (cli.py) replays a log into a
run summary; :func:`summarize_log` is the library form.  The v1 analog of
this file is ``Stat::printAllStatus`` driven by ``log_period``
(utils/Stat.h:230, Flags.cpp:62) — here the period lives in the trainer
(:func:`maybe_periodic_report`) and the sink is structured, not stdout.
"""
from __future__ import annotations

import json
import logging
import threading
import time
from typing import Dict, List, Optional

from . import metrics as _metrics

logger = logging.getLogger("paddle_tpu")

__all__ = [
    "log_path", "emit_event", "metrics_snapshot", "sample_device_memory",
    "periodic_report", "maybe_periodic_report", "summarize_log",
]


def log_path() -> str:
    """Active JSONL metrics log path ('' = disabled)."""
    try:
        from .. import flags
        return str(flags.get_flag("metrics_log") or "")
    except KeyError:
        return ""


class _Writer:
    """Lazily-opened, thread-safe, line-buffered JSONL appender that
    follows the ``metrics_log`` flag (a changed path reopens)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._path: Optional[str] = None
        self._fh = None

    def emit(self, kind: str, payload: dict):
        path = log_path()
        if not path:
            return
        line = json.dumps({"ts": round(time.time(), 6), "kind": kind,
                           **payload}, default=repr)
        with self._lock:
            if self._path != path:
                if self._fh is not None:
                    self._fh.close()
                self._fh, self._path = None, path
                try:
                    self._fh = open(path, "a")
                except OSError as e:
                    logger.warning("metrics log %r unwritable (%s); "
                                   "disabling until the path changes",
                                   path, e)
            if self._fh is None:       # disabled: an earlier open/write
                return                 # on this path failed
            try:
                self._fh.write(line + "\n")
                self._fh.flush()
            except OSError as e:
                logger.warning("metrics log %r write failed (%s); "
                               "disabling until the path changes", path, e)
                try:
                    self._fh.close()
                except OSError:
                    pass               # already broken; disabling anyway
                self._fh = None        # path unchanged -> stays disabled

    def close(self):
        with self._lock:
            if self._fh is not None:
                self._fh.close()
            self._fh, self._path = None, None


_writer = _Writer()


def emit_event(kind: str, **payload):
    """Append one structured event to the JSONL log (no-op when the
    ``metrics_log`` flag is empty)."""
    _writer.emit(kind, payload)


def _reset_writer():
    """Close the writer (tests; also safe any time — next emit reopens)."""
    _writer.close()


# ---------------------------------------------------------------------------
# Snapshots
# ---------------------------------------------------------------------------
_mem_supported: Optional[bool] = None


def sample_device_memory() -> Dict[str, dict]:
    """Per-device ``memory_stats()`` where the backend provides them
    (TPU/GPU PJRT backends do; CPU returns nothing).  Also mirrors
    bytes_in_use/peak into the device/* gauges.  Returns {} when
    unsupported and remembers that, so hot-path callers pay one probe."""
    global _mem_supported
    if _mem_supported is False:
        return {}
    import jax
    out: Dict[str, dict] = {}
    for d in jax.local_devices():
        try:
            ms = d.memory_stats()
        except Exception as e:   # backend without the PJRT memory API
            logger.debug("memory_stats unavailable on %s: %s", d, e)
            _mem_supported = False
            return {}
        if not ms:
            _mem_supported = False
            return {}
        label = f"{d.platform}:{d.id}"
        out[label] = {k: int(v) for k, v in ms.items()}
        if "bytes_in_use" in ms:
            _metrics.set_gauge("device/bytes_in_use", ms["bytes_in_use"],
                               label=label)
        if "peak_bytes_in_use" in ms:
            _metrics.set_gauge("device/peak_bytes_in_use",
                               ms["peak_bytes_in_use"], label=label)
    _mem_supported = True
    return out


def metrics_snapshot() -> dict:
    """One merged, JSON-serializable view of the whole runtime:

    * ``metrics``  — every registry metric (counters/gauges/histograms),
    * ``compile``  — ``CompileStats`` counters re-keyed ``compile/<name>``
      (hits/misses/evictions/traces/... — see core/compile_cache.py),
    * ``device_memory`` — per-device memory_stats where supported.
    """
    from ..core import compile_cache
    return {
        "metrics": _metrics.registry().snapshot(),
        "compile": {f"compile/{k}": v
                    for k, v in compile_cache.stats().snapshot().items()},
        "device_memory": sample_device_memory(),
    }


# ---------------------------------------------------------------------------
# Periodic reports (the log_period wiring)
# ---------------------------------------------------------------------------
def periodic_report(step: int):
    """Emit one merged report: StatSet+CompileStats+Metrics text at INFO,
    plus a ``snapshot`` event in the JSONL log."""
    from .. import profiler
    _metrics.inc_counter("trainer/reports")
    logger.info("observability report @ step %d\n%s", step,
                profiler.report())
    emit_event("snapshot", step=step, **metrics_snapshot())


def maybe_periodic_report(iters_done: int,
                          observing: Optional[bool] = None) -> bool:
    """Trainer hook: fire :func:`periodic_report` every ``log_period``
    iterations (the hitherto-dead Flags.cpp:62 knob).  ``observing``
    overrides the global flag (an ``Executor(observe=True)`` trainer
    reports even when the process-wide flag is off).  Returns whether a
    report fired."""
    if not (_metrics.enabled() if observing is None else observing):
        return False
    try:
        from .. import flags
        period = int(flags.get_flag("log_period"))
    except (KeyError, TypeError, ValueError):
        return False
    if period <= 0 or iters_done <= 0 or iters_done % period:
        return False
    periodic_report(iters_done)
    return True


# ---------------------------------------------------------------------------
# Log summarization (the `python -m paddle_tpu stats` engine)
# ---------------------------------------------------------------------------
def summarize_log(path: str) -> dict:
    """Aggregate a JSONL metrics log into one run summary dict.

    Tolerates corrupt lines (counted, not fatal); raises OSError for an
    unreadable file (the CLI wraps it)."""
    steps: List[dict] = []
    nans: List[dict] = []
    faults: List[dict] = []
    servings: List[dict] = []
    tunings: List[dict] = []
    last_snapshot: Optional[dict] = None
    snapshots = corrupt = total = 0
    t_first = t_last = None
    with open(path) as fh:
        for line in fh:
            line = line.strip()
            if not line:
                continue
            total += 1
            try:
                ev = json.loads(line)
            except json.JSONDecodeError:
                corrupt += 1
                continue
            ts = ev.get("ts")
            if isinstance(ts, (int, float)):
                t_first = ts if t_first is None else t_first
                t_last = ts
            kind = ev.get("kind")
            if kind == "step":
                steps.append(ev)
            elif kind == "snapshot":
                snapshots += 1
                last_snapshot = ev
            elif kind == "nan":
                nans.append(ev)
            elif kind == "fault":
                faults.append(ev)
            elif kind == "serving":
                servings.append(ev)
            elif kind == "tuning":
                tunings.append(ev)

    summary: dict = {
        "events": total, "corrupt_lines": corrupt,
        "snapshots": snapshots, "nan_events": len(nans),
        "wall_s": round(t_last - t_first, 3)
        if t_first is not None and t_last is not None else None,
    }
    if steps:
        n_steps = sum(int(e.get("steps", 1)) for e in steps)
        # cold dispatches (trace/compile happened inside the call) carry
        # step_ms=None by design — compile time must not read as step time
        step_ms = sorted(float(e["step_ms"]) for e in steps
                         if e.get("step_ms") is not None)
        feed_b = sum(float(e.get("feed_bytes", 0)) for e in steps)
        wall_s = sum(float(e.get("wall_ms", 0)) for e in steps) / 1e3
        summary["steps"] = {
            "dispatches": len(steps), "steps": n_steps,
            "cold_dispatches": sum(1 for e in steps
                                   if e.get("cold_compile")),
            "step_ms_mean": round(sum(step_ms) / len(step_ms), 3)
            if step_ms else None,
            "step_ms_p50": round(step_ms[len(step_ms) // 2], 3)
            if step_ms else None,
            "step_ms_p90": round(step_ms[int(len(step_ms) * 0.9)
                                         - (len(step_ms) == 1)], 3)
            if step_ms else None,
            "feed_mb": round(feed_b / 2 ** 20, 3),
            "steps_per_sec": round(n_steps / wall_s, 2) if wall_s else None,
        }
    if last_snapshot is not None:
        hists = {}
        for name, snap in (last_snapshot.get("metrics") or {}).items():
            if snap.get("kind") == "histogram" and snap.get("count"):
                hists[name] = {
                    "count": snap["count"],
                    "mean": round(snap["sum"] / snap["count"], 3),
                    "p50": round(_metrics.histogram_quantile(snap, 0.5), 3),
                    "p90": round(_metrics.histogram_quantile(snap, 0.9), 3),
                    "max": snap["max"],
                }
        counters = {
            name: snap["value"]
            for name, snap in (last_snapshot.get("metrics") or {}).items()
            if snap.get("kind") == "counter" and snap.get("value")}
        busy = counters.get("pipeline/worker_busy_s", 0.0)
        wait = counters.get("pipeline/worker_wait_s", 0.0)
        summary["last_snapshot"] = {
            "histograms": hists, "counters": counters,
            "compile": last_snapshot.get("compile") or {},
            "worker_busy_fraction": round(busy / (busy + wait), 4)
            if busy + wait > 0 else None,
        }
    if nans:
        summary["nan"] = [{k: e.get(k) for k in
                           ("op_index", "op_type", "var", "phase")}
                          for e in nans[:5]]
    if faults:
        by_event: Dict[str, int] = {}
        for e in faults:
            key = str(e.get("event", "unknown"))
            by_event[key] = by_event.get(key, 0) + 1
        summary["faults"] = {
            "events": len(faults), "by_event": by_event,
            # first few, enough to see a run's failure story at a glance
            "timeline": [{k: e.get(k) for k in
                          ("event", "site", "index", "action", "step",
                           "attempt", "error", "delay_s")
                          if e.get(k) is not None}
                         for e in faults[:10]],
        }
    if servings:
        by_event: Dict[str, int] = {}
        models = set()
        batches = [e for e in servings if e.get("event") == "batch"]
        for e in servings:
            key = str(e.get("event", "unknown"))
            by_event[key] = by_event.get(key, 0) + 1
            if e.get("model"):
                models.add(str(e["model"]))
        served = sum(int(e.get("size", 0)) for e in batches)
        sizes = [int(e.get("size", 0)) for e in batches]
        dms = sorted(float(e["dispatch_ms"]) for e in batches
                     if e.get("dispatch_ms") is not None)
        summary["serving"] = {
            "events": len(servings), "by_event": by_event,
            "models": sorted(models),
            "batches": len(batches), "requests_served": served,
            "batch_size_mean": round(sum(sizes) / len(sizes), 2)
            if sizes else None,
            "dispatch_ms_p50": round(dms[len(dms) // 2], 3)
            if dms else None,
            "shed": by_event.get("shed", 0),
            "deadline_expired": by_event.get("deadline_expired", 0),
            "breaker_opens": by_event.get("breaker_open", 0),
            "states": [str(e.get("state")) for e in servings
                       if e.get("event") == "state"],
        }
    if tunings:
        by_event: Dict[str, int] = {}
        for e in tunings:
            key = str(e.get("event", "unknown"))
            by_event[key] = by_event.get(key, 0) + 1
        summary["tuning"] = {
            "events": len(tunings), "by_event": by_event,
            "trials": by_event.get("trial", 0),
            "winners": [{"tunable": e.get("tunable"),
                         "config": e.get("config"),
                         "speedup": e.get("speedup")}
                        for e in tunings if e.get("event") == "winner"],
            "refusals": [{"tunable": e.get("tunable"),
                          "reason": e.get("reason"),
                          "speedup": e.get("speedup")}
                         for e in tunings if e.get("event") == "refusal"],
            "replays": [{"tunable": e.get("tunable"),
                         "config": e.get("config")}
                        for e in tunings if e.get("event") == "replay"],
        }
    return summary


def render_summary(summary: dict) -> str:
    """Human-readable rendering of :func:`summarize_log` output."""
    lines = [f"events={summary['events']} "
             f"snapshots={summary['snapshots']} "
             f"nan_events={summary['nan_events']} "
             f"corrupt_lines={summary['corrupt_lines']}"
             + (f" wall_s={summary['wall_s']}"
                if summary.get("wall_s") is not None else "")]
    st = summary.get("steps")
    if st:
        lines.append(
            f"steps: {st['steps']} in {st['dispatches']} dispatches, "
            f"step_ms mean={st['step_ms_mean']} p50={st['step_ms_p50']} "
            f"p90={st['step_ms_p90']}, feed={st['feed_mb']} MB"
            + (f", {st['steps_per_sec']} steps/s"
               if st.get("steps_per_sec") else ""))
    snap = summary.get("last_snapshot")
    if snap:
        for name, h in sorted(snap["histograms"].items()):
            lines.append(f"  {name}: count={h['count']} mean={h['mean']} "
                         f"p50={h['p50']} p90={h['p90']} max={h['max']}")
        for name, v in sorted(snap["counters"].items()):
            lines.append(f"  {name}: {v:g}")
        if snap.get("worker_busy_fraction") is not None:
            lines.append(
                f"  pipeline worker busy fraction: "
                f"{snap['worker_busy_fraction']}")
    for n in summary.get("nan", []):
        lines.append(f"  NaN: op #{n.get('op_index')} {n.get('op_type')!r} "
                     f"-> {n.get('var')!r} ({n.get('phase')})")
    fl = summary.get("faults")
    if fl:
        kinds = " ".join(f"{k}={v}" for k, v in sorted(
            fl["by_event"].items()))
        lines.append(f"faults: {fl['events']} event(s): {kinds}")
        for e in fl["timeline"]:
            lines.append("  fault: " + " ".join(
                f"{k}={e[k]}" for k in ("event", "site", "index", "action",
                                        "step", "attempt", "delay_s",
                                        "error") if k in e))
    sv = summary.get("serving")
    if sv:
        lines.append(
            f"serving: {sv['requests_served']} request(s) in "
            f"{sv['batches']} batch(es)"
            + (f", mean batch {sv['batch_size_mean']}"
               if sv.get("batch_size_mean") is not None else "")
            + (f", dispatch p50 {sv['dispatch_ms_p50']} ms"
               if sv.get("dispatch_ms_p50") is not None else "")
            + f" [models: {', '.join(sv['models'])}]")
        lines.append(
            f"  shed={sv['shed']} deadline_expired={sv['deadline_expired']}"
            f" breaker_opens={sv['breaker_opens']}"
            + (f" states={'→'.join(sv['states'])}" if sv["states"] else ""))
    tu = summary.get("tuning")
    if tu:
        kinds = " ".join(f"{k}={v}" for k, v in sorted(
            tu["by_event"].items()))
        lines.append(f"tuning: {tu['events']} event(s): {kinds}")
        for w in tu["winners"]:
            lines.append(f"  winner: {w['tunable']} -> {w['config']} "
                         f"({w['speedup']}x)")
        for r in tu["refusals"]:
            lines.append(f"  refusal: {r['tunable']} — {r['reason']}")
        for r in tu["replays"]:
            lines.append(f"  replay: {r['tunable']} -> {r['config']}")
    return "\n".join(lines)
