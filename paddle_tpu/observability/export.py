"""Export & surfacing: JSONL structured log, merged snapshots, device
memory sampling, periodic reports, and the ``stats`` CLI summarizer.

The JSONL log (flag ``metrics_log`` / env ``PADDLE_TPU_METRICS_LOG``) is
an append-only stream of one-line JSON events::

    {"ts": <unix s>, "kind": "step",     ...per-dispatch telemetry}
    {"ts": <unix s>, "kind": "snapshot", ...metrics_snapshot() payload}
    {"ts": <unix s>, "kind": "nan",      ...NaN-provenance diagnostic}

``python -m paddle_tpu stats run.jsonl`` (cli.py) replays a log into a
run summary; :func:`summarize_log` is the library form.  The v1 analog of
this file is ``Stat::printAllStatus`` driven by ``log_period``
(utils/Stat.h:230, Flags.cpp:62) — here the period lives in the trainer
(:func:`maybe_periodic_report`) and the sink is structured, not stdout.
"""
from __future__ import annotations

import json
import logging
import os
import threading
import time
from typing import Dict, List, Optional

from . import metrics as _metrics
from ..testing import lockwatch as _lw

logger = logging.getLogger("paddle_tpu")

__all__ = [
    "log_path", "emit_event", "metrics_snapshot", "sample_device_memory",
    "periodic_report", "maybe_periodic_report", "summarize_log",
    "summarize_logs", "iter_log_events", "to_prometheus", "prom_name",
    "metric_name_from_prom", "set_process_identity", "process_identity",
    "source_label",
]

# Who this process is, stamped into every (re)opened JSONL log as the
# first event the writer appends — multi-file merges then label sources
# "pserver:1" instead of a bare argument index.  Process mains
# (pserver/serve/fleet/master CLIs) set this before their first emit.
_identity = {"role": None, "index": None}


def set_process_identity(role: Optional[str],
                         index: Optional[int] = None):
    """Declare this process's role (``trainer``/``pserver``/``serve``/
    ``fleet``/...) and optional shard/replica index for JSONL identity
    stamping.  ``None`` resets to the default (``main``)."""
    _identity["role"] = None if role is None else str(role)
    _identity["index"] = None if index is None else int(index)


def process_identity() -> dict:
    """This process's stamped identity — ``{"role", "pid"[, "index"]}``
    (role defaults to ``main``); what wire-metrics piggybacks attach so
    the fleet collector labels each snapshot's source."""
    out = {"role": _identity["role"] or "main", "pid": os.getpid()}
    if _identity["index"] is not None:
        out["index"] = _identity["index"]
    return out


def source_label(f: dict) -> str:
    """Human label for one merged-log source: ``role`` or ``role:index``
    when the log stamped identity, else the bare argument position."""
    role = f.get("role")
    if role:
        idx = f.get("proc_index")
        return f"{role}:{idx}" if idx is not None else str(role)
    return str(f.get("index", "?"))


def log_path() -> str:
    """Active JSONL metrics log path ('' = disabled)."""
    try:
        from .. import flags
        return str(flags.get_flag("metrics_log") or "")
    except KeyError:
        return ""


class _Writer:
    """Lazily-opened, thread-safe, line-buffered JSONL appender that
    follows the ``metrics_log`` flag (a changed path reopens)."""

    def __init__(self):
        self._lock = _lw.make_lock("observability.export")
        self._path: Optional[str] = None
        self._fh = None

    def emit(self, kind: str, payload: dict):
        path = log_path()
        if not path:
            return
        line = json.dumps({"ts": round(time.time(), 6), "kind": kind,
                           **payload}, default=repr)
        with self._lock:
            if self._path != path:
                if self._fh is not None:
                    self._fh.close()
                self._fh, self._path = None, path
                try:
                    self._fh = open(path, "a")
                    # identity header: first line this process appends
                    # to a (re)opened log — role/pid/index label every
                    # event that follows in multi-file merges
                    ident = {"ts": round(time.time(), 6),
                             "kind": "identity",
                             "role": _identity["role"] or "main",
                             "pid": os.getpid()}
                    if _identity["index"] is not None:
                        ident["index"] = _identity["index"]
                    self._fh.write(json.dumps(ident) + "\n")
                    self._fh.flush()
                except OSError as e:
                    logger.warning("metrics log %r unwritable (%s); "
                                   "disabling until the path changes",
                                   path, e)
                    if self._fh is not None:
                        try:
                            self._fh.close()
                        except OSError:
                            pass
                    self._fh = None
            if self._fh is None:       # disabled: an earlier open/write
                return                 # on this path failed
            try:
                self._fh.write(line + "\n")
                self._fh.flush()
            except OSError as e:
                logger.warning("metrics log %r write failed (%s); "
                               "disabling until the path changes", path, e)
                try:
                    self._fh.close()
                except OSError:
                    pass               # already broken; disabling anyway
                self._fh = None        # path unchanged -> stays disabled

    def close(self):
        with self._lock:
            if self._fh is not None:
                self._fh.close()
            self._fh, self._path = None, None


_writer = _Writer()


def emit_event(kind: str, **payload):
    """Append one structured event to the JSONL log (no-op when the
    ``metrics_log`` flag is empty)."""
    _writer.emit(kind, payload)


def _reset_writer():
    """Close the writer (tests; also safe any time — next emit reopens)."""
    _writer.close()


# ---------------------------------------------------------------------------
# Snapshots
# ---------------------------------------------------------------------------
_mem_supported: Optional[bool] = None


def sample_device_memory() -> Dict[str, dict]:
    """Per-device ``memory_stats()`` where the backend provides them
    (TPU/GPU PJRT backends do; CPU returns nothing).  Also mirrors
    bytes_in_use/peak into the device/* gauges.  Returns {} when
    unsupported and remembers that, so hot-path callers pay one probe."""
    global _mem_supported
    if _mem_supported is False:
        return {}
    import jax
    out: Dict[str, dict] = {}
    for d in jax.local_devices():
        try:
            ms = d.memory_stats()
        except Exception as e:   # backend without the PJRT memory API
            logger.debug("memory_stats unavailable on %s: %s", d, e)
            _mem_supported = False
            return {}
        if not ms:
            _mem_supported = False
            return {}
        label = f"{d.platform}:{d.id}"
        out[label] = {k: int(v) for k, v in ms.items()}
        if "bytes_in_use" in ms:
            _metrics.set_gauge("device/bytes_in_use", ms["bytes_in_use"],
                               label=label)
        if "peak_bytes_in_use" in ms:
            _metrics.set_gauge("device/peak_bytes_in_use",
                               ms["peak_bytes_in_use"], label=label)
    _mem_supported = True
    return out


def metrics_snapshot() -> dict:
    """One merged, JSON-serializable view of the whole runtime:

    * ``metrics``  — every registry metric (counters/gauges/histograms),
    * ``compile``  — ``CompileStats`` counters re-keyed ``compile/<name>``
      (hits/misses/evictions/traces/... — see core/compile_cache.py),
    * ``device_memory`` — per-device memory_stats where supported.
    """
    from ..core import compile_cache
    return {
        "metrics": _metrics.registry().snapshot(),
        "compile": {f"compile/{k}": v
                    for k, v in compile_cache.stats().snapshot().items()},
        "device_memory": sample_device_memory(),
    }


# ---------------------------------------------------------------------------
# Periodic reports (the log_period wiring)
# ---------------------------------------------------------------------------
def periodic_report(step: int):
    """Emit one merged report: StatSet+CompileStats+Metrics text at INFO,
    plus a ``snapshot`` event in the JSONL log."""
    from .. import profiler
    _metrics.inc_counter("trainer/reports")
    logger.info("observability report @ step %d\n%s", step,
                profiler.report())
    emit_event("snapshot", step=step, **metrics_snapshot())


def maybe_periodic_report(iters_done: int,
                          observing: Optional[bool] = None) -> bool:
    """Trainer hook: fire :func:`periodic_report` every ``log_period``
    iterations (the hitherto-dead Flags.cpp:62 knob).  ``observing``
    overrides the global flag (an ``Executor(observe=True)`` trainer
    reports even when the process-wide flag is off).  Returns whether a
    report fired."""
    if not (_metrics.enabled() if observing is None else observing):
        return False
    try:
        from .. import flags
        period = int(flags.get_flag("log_period"))
    except (KeyError, TypeError, ValueError):
        return False
    if period <= 0 or iters_done <= 0 or iters_done % period:
        return False
    periodic_report(iters_done)
    return True


# ---------------------------------------------------------------------------
# Log reading (shared by the `stats` / `trace` / `doctor` engines)
# ---------------------------------------------------------------------------
def iter_log_events(paths) -> "tuple[List[dict], List[dict]]":
    """Read one or more JSONL logs, merged in time order.

    A supervised run that resumed after SIGTERM/exit-75 produces one log
    per relaunch; summaries should span the whole job, so every CLI
    consumer accepts multiple files.  Returns ``(events, files)`` where
    ``files`` records per-file boundaries (path, first/last ts, event and
    corrupt-line counts) — the restart markers the timeline renders.

    Robustness (the chaos suite's SIGKILL mid-write case): a torn or
    truncated final line — including one cut inside a multi-byte UTF-8
    character — is skipped and counted, never fatal (``errors="replace"``
    keeps the read itself from raising ``UnicodeDecodeError``).  Raises
    OSError only for an unreadable file (the CLI wraps it).
    """
    if isinstance(paths, (str, os.PathLike)):
        paths = [paths]
    events: List[dict] = []
    files: List[dict] = []
    for src, path in enumerate(paths):
        n = corrupt = 0
        t_first = t_last = None
        role = pid = proc_index = None
        with open(path, errors="replace") as fh:
            for line in fh:
                line = line.strip()
                if not line:
                    continue
                n += 1
                try:
                    ev = json.loads(line)
                    if not isinstance(ev, dict):
                        raise json.JSONDecodeError("not an object", line, 0)
                except json.JSONDecodeError:
                    corrupt += 1
                    continue
                if ev.get("kind") == "identity" and role is None:
                    # the writer's open-time stamp: the FIRST one names
                    # the process this file belongs to (appends from a
                    # relaunch re-stamp, but the role stays the same)
                    role = ev.get("role")
                    pid = ev.get("pid")
                    proc_index = ev.get("index")
                ts = ev.get("ts")
                if isinstance(ts, (int, float)) \
                        and not isinstance(ts, bool):
                    t_first = ts if t_first is None else t_first
                    t_last = ts
                else:
                    # every writer stamps a numeric ts; a foreign/hand-
                    # edited line with a missing or string ts must stay
                    # mergeable (the sort key is numeric), not crash the
                    # summary — coerce to the file position
                    ev = {**ev, "ts": t_last if t_last is not None
                          else 0.0}
                # carry the SOURCE FILE index through the time-ordered
                # merge: relaunch logs interleave by coerced ts only, so
                # without it a rendered row cannot be attributed to the
                # right attempt (the `files` list maps index -> path)
                ev["_src"] = src
                events.append(ev)
        if corrupt:
            logger.warning("metrics log %r: skipped %d corrupt/truncated "
                           "line(s) (torn writes from a killed process "
                           "are expected; the summary continues)",
                           str(path), corrupt)
        files.append({"file": str(path), "index": src, "events": n,
                      "corrupt_lines": corrupt,
                      "t_first": t_first, "t_last": t_last,
                      "role": role, "pid": pid,
                      "proc_index": proc_index})
    if len(files) > 1:
        files.sort(key=lambda f: (f["t_first"] is None,
                                  f["t_first"] or 0.0))
        events.sort(key=lambda e: e.get("ts", 0.0))
    return events, files


# ---------------------------------------------------------------------------
# Log summarization (the `python -m paddle_tpu stats` engine)
# ---------------------------------------------------------------------------
def summarize_log(path: str) -> dict:
    """Aggregate ONE JSONL metrics log into a run summary dict (see
    :func:`summarize_logs` for the multi-file / resumed-job form)."""
    return summarize_logs([path])


def summarize_logs(paths) -> dict:
    """Aggregate one or more JSONL metrics logs (merged in time order —
    a resumed job's per-relaunch logs summarize as one run) into one
    summary dict.  Tolerates corrupt/torn lines (counted, not fatal);
    raises OSError for an unreadable file (the CLI wraps it)."""
    events, files = iter_log_events(paths)
    steps: List[dict] = []
    nans: List[dict] = []
    faults: List[dict] = []
    servings: List[dict] = []
    tunings: List[dict] = []
    pservers: List[dict] = []
    ckpts: List[dict] = []
    spans = 0
    last_snapshot: Optional[dict] = None
    snapshots = 0
    t_first = t_last = None
    for ev in events:
        ts = ev.get("ts")
        if isinstance(ts, (int, float)):
            t_first = ts if t_first is None else min(t_first, ts)
            t_last = ts if t_last is None else max(t_last, ts)
        kind = ev.get("kind")
        if kind == "step":
            steps.append(ev)
        elif kind == "snapshot":
            snapshots += 1
            last_snapshot = ev
        elif kind == "nan":
            nans.append(ev)
        elif kind == "fault":
            faults.append(ev)
        elif kind == "serving":
            servings.append(ev)
        elif kind == "tuning":
            tunings.append(ev)
        elif kind == "pserver":
            pservers.append(ev)
        elif kind == "ckpt":
            ckpts.append(ev)
        elif kind == "span":
            spans += 1

    total = sum(f["events"] for f in files)
    corrupt = sum(f["corrupt_lines"] for f in files)
    summary: dict = {
        "events": total, "corrupt_lines": corrupt,
        "snapshots": snapshots, "nan_events": len(nans),
        "spans": spans,
        "wall_s": round(t_last - t_first, 3)
        if t_first is not None and t_last is not None else None,
    }
    if len(files) > 1:
        # restart boundaries: where each relaunch's log begins; "source"
        # is the index fault-timeline rows carry (the original argument
        # position, stable across the time-order sort); "role" labels
        # it by process identity when the log stamped one
        summary["restarts"] = [
            {"file": f["file"], "source": f["index"], "ts": f["t_first"],
             "events": f["events"],
             **({"role": source_label(f)} if f.get("role") else {})}
            for f in files]
    if steps:
        n_steps = sum(int(e.get("steps", 1)) for e in steps)
        # cold dispatches (trace/compile happened inside the call) carry
        # step_ms=None by design — compile time must not read as step time
        step_ms = sorted(float(e["step_ms"]) for e in steps
                         if e.get("step_ms") is not None)
        feed_b = sum(float(e.get("feed_bytes", 0)) for e in steps)
        wall_s = sum(float(e.get("wall_ms", 0)) for e in steps) / 1e3
        summary["steps"] = {
            "dispatches": len(steps), "steps": n_steps,
            "cold_dispatches": sum(1 for e in steps
                                   if e.get("cold_compile")),
            "step_ms_mean": round(sum(step_ms) / len(step_ms), 3)
            if step_ms else None,
            "step_ms_p50": round(step_ms[len(step_ms) // 2], 3)
            if step_ms else None,
            "step_ms_p90": round(step_ms[int(len(step_ms) * 0.9)
                                         - (len(step_ms) == 1)], 3)
            if step_ms else None,
            "feed_mb": round(feed_b / 2 ** 20, 3),
            "steps_per_sec": round(n_steps / wall_s, 2) if wall_s else None,
        }
    if last_snapshot is not None:
        hists = {}
        for name, snap in (last_snapshot.get("metrics") or {}).items():
            if snap.get("kind") == "histogram" and snap.get("count"):
                hists[name] = {
                    "count": snap["count"],
                    "mean": round(snap["sum"] / snap["count"], 3),
                    "p50": round(_metrics.histogram_quantile(snap, 0.5), 3),
                    "p90": round(_metrics.histogram_quantile(snap, 0.9), 3),
                    "max": snap["max"],
                }
        counters = {
            name: snap["value"]
            for name, snap in (last_snapshot.get("metrics") or {}).items()
            if snap.get("kind") == "counter" and snap.get("value")}
        busy = counters.get("pipeline/worker_busy_s", 0.0)
        wait = counters.get("pipeline/worker_wait_s", 0.0)
        summary["last_snapshot"] = {
            "histograms": hists, "counters": counters,
            "compile": last_snapshot.get("compile") or {},
            "worker_busy_fraction": round(busy / (busy + wait), 4)
            if busy + wait > 0 else None,
        }
    if nans:
        summary["nan"] = [{k: e.get(k) for k in
                           ("op_index", "op_type", "var", "phase")}
                          for e in nans[:5]]
    if faults:
        by_event: Dict[str, int] = {}
        for e in faults:
            key = str(e.get("event", "unknown"))
            by_event[key] = by_event.get(key, 0) + 1
        multi = len(files) > 1
        roles = {f["index"]: source_label(f) for f in files
                 if f.get("role")}

        def _fault_row(e):
            row = {k: e.get(k) for k in
                   ("event", "site", "index", "action", "step",
                    "attempt", "error", "delay_s")
                   if e.get(k) is not None}
            if multi:
                # a merged timeline interleaves relaunch logs by ts
                # only; the source-file index makes each row
                # attributable to the right attempt (plus the role
                # label when that file stamped identity)
                row["source"] = e.get("_src")
                if e.get("_src") in roles:
                    row["role"] = roles[e["_src"]]
            return row

        summary["faults"] = {
            "events": len(faults), "by_event": by_event,
            # first few, enough to see a run's failure story at a glance
            "timeline": [_fault_row(e) for e in faults[:10]],
        }
    if servings:
        by_event: Dict[str, int] = {}
        models = set()
        batches = [e for e in servings if e.get("event") == "batch"]
        for e in servings:
            key = str(e.get("event", "unknown"))
            by_event[key] = by_event.get(key, 0) + 1
            if e.get("model"):
                models.add(str(e["model"]))
        served = sum(int(e.get("size", 0)) for e in batches)
        sizes = [int(e.get("size", 0)) for e in batches]
        dms = sorted(float(e["dispatch_ms"]) for e in batches
                     if e.get("dispatch_ms") is not None)
        summary["serving"] = {
            "events": len(servings), "by_event": by_event,
            "models": sorted(models),
            "batches": len(batches), "requests_served": served,
            "batch_size_mean": round(sum(sizes) / len(sizes), 2)
            if sizes else None,
            "dispatch_ms_p50": round(dms[len(dms) // 2], 3)
            if dms else None,
            "shed": by_event.get("shed", 0),
            "deadline_expired": by_event.get("deadline_expired", 0),
            "breaker_opens": by_event.get("breaker_open", 0),
            "states": [str(e.get("state")) for e in servings
                       if e.get("event") == "state"],
        }
        dec_steps = [e for e in servings if e.get("event") == "decode_step"]
        dec_done = [e for e in servings if e.get("event") == "decode_done"]
        if dec_steps or dec_done:
            active = [int(e.get("active", 0)) for e in dec_steps]
            sdms = sorted(float(e["dispatch_ms"]) for e in dec_steps
                          if e.get("dispatch_ms") is not None)
            ttfts = sorted(float(e["ttft_ms"]) for e in servings
                           if e.get("event") == "decode_admit"
                           and e.get("ttft_ms") is not None)
            summary["decode"] = {
                "steps": len(dec_steps),
                "sequences_done": len(dec_done),
                "tokens": sum(int(e.get("tokens", 0)) for e in dec_done),
                "active_mean": round(sum(active) / len(active), 2)
                if active else None,
                "step_ms_p50": round(sdms[len(sdms) // 2], 3)
                if sdms else None,
                "ttft_ms_p50": round(ttfts[len(ttfts) // 2], 3)
                if ttfts else None,
                "by_finish": {
                    f: sum(1 for e in dec_done if e.get("finish") == f)
                    for f in sorted({str(e.get("finish"))
                                     for e in dec_done})},
            }
    if tunings:
        by_event: Dict[str, int] = {}
        for e in tunings:
            key = str(e.get("event", "unknown"))
            by_event[key] = by_event.get(key, 0) + 1
        summary["tuning"] = {
            "events": len(tunings), "by_event": by_event,
            "trials": by_event.get("trial", 0),
            "winners": [{"tunable": e.get("tunable"),
                         "config": e.get("config"),
                         "speedup": e.get("speedup")}
                        for e in tunings if e.get("event") == "winner"],
            "refusals": [{"tunable": e.get("tunable"),
                          "reason": e.get("reason"),
                          "speedup": e.get("speedup")}
                         for e in tunings if e.get("event") == "refusal"],
            "replays": [{"tunable": e.get("tunable"),
                         "config": e.get("config")}
                        for e in tunings if e.get("event") == "replay"],
        }
    if pservers:
        by_event: Dict[str, int] = {}
        shards = set()
        for e in pservers:
            key = str(e.get("event", "unknown"))
            by_event[key] = by_event.get(key, 0) + 1
            if e.get("shard") is not None:
                shards.add(int(e["shard"]))
        shut = [e for e in pservers if e.get("event") == "shutdown"]
        summary["pserver"] = {
            "events": len(pservers), "by_event": by_event,
            "shards": sorted(shards),
            "checkpoints": by_event.get("checkpoint", 0),
            "restores": [{"shard": e.get("shard"),
                          "source": e.get("source"),
                          "pushes_applied": e.get("pushes_applied")}
                         for e in pservers
                         if e.get("event") == "restore"],
            "pulls": sum(int(e.get("pulls", 0)) for e in shut),
            "pushes": sum(int(e.get("pushes", 0)) for e in shut),
            "wire_mb_in": round(sum(
                float(e.get("wire_bytes_in", 0)) for e in shut) / 2 ** 20,
                3),
            "wire_mb_out": round(sum(
                float(e.get("wire_bytes_out", 0))
                for e in shut) / 2 ** 20, 3),
        }
    if ckpts:
        commits = [e for e in ckpts if e.get("event") == "commit"]
        fulls = [e for e in commits if e.get("commit_kind") == "full"]
        deltas = [e for e in commits if e.get("commit_kind") == "delta"]
        cms = sorted(float(e["ms"]) for e in commits
                     if e.get("ms") is not None)
        summary["checkpoint"] = {
            "events": len(ckpts), "commits": len(commits),
            "full": len(fulls), "delta": len(deltas),
            "rebases": sum(1 for e in commits if e.get("rebase")),
            "delta_mb": round(sum(float(e.get("bytes", 0))
                                  for e in deltas) / 2 ** 20, 3),
            "delta_rows": sum(int(e.get("rows", 0)) for e in deltas),
            "full_mb": round(sum(float(e.get("bytes", 0))
                                 for e in fulls) / 2 ** 20, 3),
            "commit_ms_p50": round(cms[len(cms) // 2], 3) if cms else None,
            "max_chain_len": max((int(e.get("chain_len", 0))
                                  for e in commits), default=0),
        }
    if last_snapshot is not None:
        # lock-order watchdog (testing.lockwatch): only populated when
        # the run had PADDLE_TPU_LOCKWATCH on — absent metrics mean the
        # watchdog was off, and the section is omitted entirely
        m = last_snapshot.get("metrics") or {}
        held = m.get("concurrency/lock_held_ms") or {}
        edges = ((m.get("concurrency/order_edges") or {})
                 .get("values") or {})
        if held.get("count"):
            summary["lockwatch"] = {
                "holds": held.get("count", 0),
                "held_ms_max": held.get("max"),
                "order_edges": int(edges.get("", 0)),
                "order_violations": int(
                    (m.get("concurrency/order_violations") or {})
                    .get("value", 0)),
                "long_holds": int(
                    (m.get("concurrency/long_holds") or {})
                    .get("value", 0)),
            }
    return summary


def render_summary(summary: dict) -> str:
    """Human-readable rendering of :func:`summarize_log` output."""
    lines = [f"events={summary['events']} "
             f"snapshots={summary['snapshots']} "
             f"nan_events={summary['nan_events']} "
             f"spans={summary.get('spans', 0)} "
             f"corrupt_lines={summary['corrupt_lines']}"
             + (f" wall_s={summary['wall_s']}"
                if summary.get("wall_s") is not None else "")]
    for r in summary.get("restarts", []):
        tag = r.get("role") or r.get("source", "?")
        lines.append(f"  restart boundary: [{tag}] "
                     f"{r['file']} "
                     f"({r['events']} event(s), from ts={r['ts']})")
    st = summary.get("steps")
    if st:
        lines.append(
            f"steps: {st['steps']} in {st['dispatches']} dispatches, "
            f"step_ms mean={st['step_ms_mean']} p50={st['step_ms_p50']} "
            f"p90={st['step_ms_p90']}, feed={st['feed_mb']} MB"
            + (f", {st['steps_per_sec']} steps/s"
               if st.get("steps_per_sec") else ""))
    snap = summary.get("last_snapshot")
    if snap:
        for name, h in sorted(snap["histograms"].items()):
            lines.append(f"  {name}: count={h['count']} mean={h['mean']} "
                         f"p50={h['p50']} p90={h['p90']} max={h['max']}")
        for name, v in sorted(snap["counters"].items()):
            lines.append(f"  {name}: {v:g}")
        if snap.get("worker_busy_fraction") is not None:
            lines.append(
                f"  pipeline worker busy fraction: "
                f"{snap['worker_busy_fraction']}")
    for n in summary.get("nan", []):
        lines.append(f"  NaN: op #{n.get('op_index')} {n.get('op_type')!r} "
                     f"-> {n.get('var')!r} ({n.get('phase')})")
    fl = summary.get("faults")
    if fl:
        kinds = " ".join(f"{k}={v}" for k, v in sorted(
            fl["by_event"].items()))
        lines.append(f"faults: {fl['events']} event(s): {kinds}")
        for e in fl["timeline"]:
            lines.append("  fault: " + " ".join(
                f"{k}={e[k]}" for k in ("role", "source", "event",
                                        "site", "index", "action",
                                        "step", "attempt", "delay_s",
                                        "error") if k in e))
    sv = summary.get("serving")
    if sv:
        lines.append(
            f"serving: {sv['requests_served']} request(s) in "
            f"{sv['batches']} batch(es)"
            + (f", mean batch {sv['batch_size_mean']}"
               if sv.get("batch_size_mean") is not None else "")
            + (f", dispatch p50 {sv['dispatch_ms_p50']} ms"
               if sv.get("dispatch_ms_p50") is not None else "")
            + f" [models: {', '.join(sv['models'])}]")
        lines.append(
            f"  shed={sv['shed']} deadline_expired={sv['deadline_expired']}"
            f" breaker_opens={sv['breaker_opens']}"
            + (f" states={'→'.join(sv['states'])}" if sv["states"] else ""))
    dc = summary.get("decode")
    if dc:
        lines.append(
            f"decode: {dc['tokens']} token(s) across "
            f"{dc['sequences_done']} sequence(s) in {dc['steps']} "
            f"step(s)"
            + (f", mean active {dc['active_mean']}"
               if dc.get("active_mean") is not None else "")
            + (f", step p50 {dc['step_ms_p50']} ms"
               if dc.get("step_ms_p50") is not None else "")
            + (f", ttft p50 {dc['ttft_ms_p50']} ms"
               if dc.get("ttft_ms_p50") is not None else ""))
        if dc.get("by_finish"):
            lines.append("  finish: " + " ".join(
                f"{k}={v}" for k, v in sorted(dc["by_finish"].items())))
    tu = summary.get("tuning")
    if tu:
        kinds = " ".join(f"{k}={v}" for k, v in sorted(
            tu["by_event"].items()))
        lines.append(f"tuning: {tu['events']} event(s): {kinds}")
        for w in tu["winners"]:
            lines.append(f"  winner: {w['tunable']} -> {w['config']} "
                         f"({w['speedup']}x)")
        for r in tu["refusals"]:
            lines.append(f"  refusal: {r['tunable']} — {r['reason']}")
        for r in tu["replays"]:
            lines.append(f"  replay: {r['tunable']} -> {r['config']}")
    ps = summary.get("pserver")
    if ps:
        kinds = " ".join(f"{k}={v}" for k, v in sorted(
            ps["by_event"].items()))
        lines.append(
            f"pserver: {ps['events']} event(s) across shard(s) "
            f"{ps['shards']}: {kinds}")
        if ps["pulls"] or ps["pushes"]:
            lines.append(
                f"  served: {ps['pulls']} pull(s) {ps['pushes']} "
                f"push(es), wire {ps['wire_mb_in']} MB in / "
                f"{ps['wire_mb_out']} MB out")
        for r in ps["restores"]:
            lines.append(
                f"  restore: shard {r['shard']} from {r['source']} "
                f"(pushes_applied={r['pushes_applied']})")
    ck = summary.get("checkpoint")
    if ck:
        lines.append(
            f"checkpoint: {ck['commits']} commit(s): {ck['full']} full "
            f"({ck['full_mb']} MB) + {ck['delta']} delta "
            f"({ck['delta_mb']} MB, {ck['delta_rows']} sparse row(s)), "
            f"{ck['rebases']} rebase(s), max chain {ck['max_chain_len']}"
            + (f", commit p50 {ck['commit_ms_p50']} ms"
               if ck.get("commit_ms_p50") is not None else ""))
    lk = summary.get("lockwatch")
    if lk:
        lines.append(
            f"lockwatch: {lk['holds']} watched hold(s), "
            f"{lk['order_edges']} order edge(s), "
            f"{lk['order_violations']} violation(s), "
            f"{lk['long_holds']} long hold(s)"
            + (f", longest {lk['held_ms_max']} ms"
               if lk.get("held_ms_max") is not None else ""))
    return "\n".join(lines)


# ---------------------------------------------------------------------------
# Prometheus text exposition (scrape without a new dependency)
# ---------------------------------------------------------------------------
_PROM_PREFIX = "paddle_tpu_"


def prom_name(name: str) -> str:
    """``executor/step_time_ms`` -> ``paddle_tpu_executor_step_time_ms``.

    Reversible because metric SUBSYSTEMS (the part before ``/``) never
    contain underscores — pinned by the round-trip test against
    METRIC_NAMES, so a future subsystem cannot silently break scraping.
    """
    return _PROM_PREFIX + name.replace("/", "_")


def metric_name_from_prom(prom: str) -> str:
    """Inverse of :func:`prom_name` (accepts the ``_total`` counter
    suffix the exposition appends).

    A registered metric may itself end in ``_total`` (e.g.
    ``checkpoint/rebase_total``), so the suffix is only treated as the
    exposition's counter decoration when the full body is NOT already a
    frozen METRIC_NAMES entry.
    """
    if not prom.startswith(_PROM_PREFIX):
        raise ValueError(f"not a paddle_tpu prometheus name: {prom!r}")
    body = prom[len(_PROM_PREFIX):]

    def _split(b: str) -> str:
        sub, sep, rest = b.partition("_")
        if not sep:
            raise ValueError(f"unsplittable prometheus name: {prom!r}")
        return f"{sub}/{rest}"

    if body.endswith("_total"):
        registered = {n for n, _k, _h in _metrics.METRIC_NAMES}
        if _split(body) not in registered:
            body = body[:-len("_total")]
    return _split(body)


def _prom_escape(v: str) -> str:
    return str(v).replace("\\", "\\\\").replace('"', '\\"').replace(
        "\n", "\\n")


def _prom_num(v: float) -> str:
    """Full-precision sample formatting: ``%g``'s 6 significant digits
    would quantize large counters (feed_bytes at 3.2e9 stops moving
    between scrapes and rate() reads zero)."""
    f = float(v)
    if f.is_integer() and abs(f) < 2 ** 63:
        return str(int(f))
    return f"{f:.17g}"


def to_prometheus(snapshot: Optional[dict] = None) -> str:
    """Prometheus text exposition of a metrics snapshot.

    ``snapshot``: a :func:`metrics_snapshot` dict (its ``compile``
    counters are exposed as gauges too), a bare registry snapshot
    (``{name: metric-snap}``), or None for the live registry — so a
    serving deployment can scrape via ``python -m paddle_tpu stats
    <log> --prom`` or an in-process HTTP handler, with no new
    dependency.  Counters gain the conventional ``_total`` suffix;
    histograms expose cumulative ``_bucket``/``_sum``/``_count``.
    """
    compile_counters: Dict[str, float] = {}
    if snapshot is None:
        metrics = _metrics.registry().snapshot()
    elif "metrics" in snapshot and isinstance(snapshot["metrics"], dict):
        metrics = snapshot["metrics"]
        for k, v in (snapshot.get("compile") or {}).items():
            # "compile/hits" -> paddle_tpu_compile_hits (gauge)
            if isinstance(v, (int, float)):
                compile_counters[k] = float(v)
    else:
        metrics = snapshot
    helps = {n: h for n, _k, h in _metrics.METRIC_NAMES}
    lines: List[str] = []
    for name, snap in sorted(metrics.items()):
        base = prom_name(name)
        help_ = helps.get(name, "")
        kind = snap.get("kind")
        if kind == "counter":
            # HELP/TYPE on the _total name: in the classic text format
            # only histograms/summaries get suffix grace, so metadata on
            # the bare base would orphan the sample's family.  Don't
            # double the suffix when the metric name already carries it.
            ctr = base if base.endswith("_total") else base + "_total"
            lines.append(f"# HELP {ctr} {_prom_escape(help_)}")
            lines.append(f"# TYPE {ctr} counter")
            lines.append(f"{ctr} {_prom_num(snap['value'])}")
        elif kind == "gauge":
            if not snap["values"]:
                continue
            lines.append(f"# HELP {base} {_prom_escape(help_)}")
            lines.append(f"# TYPE {base} gauge")
            for label, v in sorted(snap["values"].items()):
                sel = f'{{label="{_prom_escape(label)}"}}' if label else ""
                lines.append(f"{base}{sel} {_prom_num(v)}")
        elif kind == "histogram":
            lines.append(f"# HELP {base} {_prom_escape(help_)}")
            lines.append(f"# TYPE {base} histogram")
            acc = 0
            for edge, c in zip(snap["boundaries"], snap["counts"]):
                acc += c
                lines.append(f'{base}_bucket{{le="{edge:g}"}} {acc}')
            lines.append(f'{base}_bucket{{le="+Inf"}} {snap["count"]}')
            lines.append(f"{base}_sum {_prom_num(snap['sum'])}")
            lines.append(f"{base}_count {snap['count']}")
    for k, v in sorted(compile_counters.items()):
        base = prom_name(k)
        lines.append(f"# TYPE {base} gauge")
        lines.append(f"{base} {_prom_num(v)}")
    return "\n".join(lines) + "\n"
