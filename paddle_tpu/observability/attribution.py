"""Perf attribution: the measured-vs-modeled "doctor" engine.

Combines three information sources into one step-time (or request-time)
**budget** that explains where the wall clock went:

* **measured span timings** — the JSONL ``step`` events and ``span``
  records PR 5 / the tracing layer emit (``kind=step`` carries per-
  dispatch wall + fetch-block time; ``pipeline/stage`` spans carry
  staging time with real timestamps, so overlap with device compute is
  computed, not guessed);
* **compiled-executable facts** — ``cost_analysis()`` /
  ``memory_analysis()`` where this jax exposes them (guarded through
  :mod:`paddle_tpu.compat`: the surface moved across 0.4.x releases);
* the **PR 7 static cost model** (``analysis.cost_model``) as the
  fallback — and as the *prediction* side of the calibration table:
  every doctored run with a program at hand records
  ``predicted_ms / measured_ms`` ratios the planner can consume later
  (ROADMAP item 2's deferred calibration, landing automatically now).

The budget decomposes the measured wall between the first dispatch start
and the last dispatch end into ``compute`` (warm dispatch wall minus
fetch block), ``fetch`` (host materialization), ``compile`` (cold
dispatches: trace/deserialize dominated), ``staging`` (stage-span time
NOT overlapped with a dispatch — overlapped staging is free by design)
and ``host_other`` (the remaining gaps: consumer stalls, feed building,
python overhead).  Components sum to the measured wall by construction;
:data:`BUDGET_TOLERANCE` pins the acceptance check
(``python -m paddle_tpu doctor`` refuses to print a budget that does
not reconcile).

This module is imported LAZILY (doctor CLI, bench drivers) — it pulls
``analysis.cost_model``, which the training hot path must never pay for
(repo-lint enforced, like serving/tuning).
"""
from __future__ import annotations

from typing import Dict, List, Optional, Tuple

__all__ = [
    "BUDGET_TOLERANCE", "step_budget", "serving_budget", "decode_budget",
    "remote_budget", "executable_facts", "calibration_row",
    "save_calibration", "save_op_class_calibration",
    "load_op_class_ratios", "doctor_report", "render_doctor",
]

# Budget components must reconcile with the measured wall within this
# fraction — the pinned acceptance tolerance (tests + the doctor CLI).
BUDGET_TOLERANCE = 0.15


# ---------------------------------------------------------------------------
# interval arithmetic (seconds, absolute unix time)
# ---------------------------------------------------------------------------
def _merge(intervals: List[Tuple[float, float]]) -> List[Tuple[float, float]]:
    out: List[Tuple[float, float]] = []
    for a, b in sorted(i for i in intervals if i[1] > i[0]):
        if out and a <= out[-1][1]:
            out[-1] = (out[-1][0], max(out[-1][1], b))
        else:
            out.append((a, b))
    return out


def _total(intervals: List[Tuple[float, float]]) -> float:
    return sum(b - a for a, b in intervals)


def _subtract(keep: List[Tuple[float, float]],
              cut: List[Tuple[float, float]]) -> List[Tuple[float, float]]:
    """Portions of ``keep`` not covered by ``cut`` (both pre-merged)."""
    out: List[Tuple[float, float]] = []
    for a, b in keep:
        cur = a
        for c, d in cut:
            if d <= cur or c >= b:
                continue
            if c > cur:
                out.append((cur, c))
            cur = max(cur, d)
            if cur >= b:
                break
        if cur < b:
            out.append((cur, b))
    return out


# ---------------------------------------------------------------------------
# step-time budget (training / pipelined path)
# ---------------------------------------------------------------------------
def step_budget(events) -> Optional[dict]:
    """Step-time budget over a log's ``step`` events + ``pipeline/stage``
    spans.  None when the log carries no dispatches.

    The measured window is [first dispatch start, last dispatch end]:
    what happens before the first dispatch (imports, model build,
    startup program) is startup, not step time."""
    steps = [e for e in events if e.get("kind") == "step"
             and isinstance(e.get("wall_ms"), (int, float))]
    if not steps:
        return None
    disp = _merge([(e["ts"] - e["wall_ms"] / 1e3, e["ts"]) for e in steps])
    t0, t1 = disp[0][0], max(b for _, b in disp)
    wall_ms = (t1 - t0) * 1e3

    cold_ms = sum(e["wall_ms"] for e in steps if e.get("cold_compile"))
    warm = [e for e in steps if not e.get("cold_compile")]
    warm_ms = sum(e["wall_ms"] for e in warm)
    fetch_ms = sum(float(e.get("fetch_block_ms") or 0.0) for e in warm)
    compute_ms = max(0.0, warm_ms - fetch_ms)

    stage_spans = [e for e in events if e.get("kind") == "span"
                   and e.get("name") == "pipeline/stage"]
    stage = _merge([(e["t0"], e["t0"] + e.get("dur_ms", 0.0) / 1e3)
                    for e in stage_spans])
    # clip staging to the measured window, then split by dispatch overlap
    stage = _subtract(stage, [(-1e18, t0), (t1, 1e18)])
    stage_total_ms = _total(stage) * 1e3
    stage_unoverlapped = _subtract(stage, disp)
    staging_ms = _total(stage_unoverlapped) * 1e3

    gap_ms = max(0.0, wall_ms - cold_ms - warm_ms)
    host_other_ms = max(0.0, gap_ms - staging_ms)
    budget = {
        "compute_ms": round(compute_ms, 3),
        "fetch_ms": round(fetch_ms, 3),
        "compile_ms": round(cold_ms, 3),
        "staging_ms": round(staging_ms, 3),
        "host_other_ms": round(host_other_ms, 3),
    }
    total = sum(budget.values())
    n_steps = sum(int(e.get("steps", 1)) for e in steps)
    warm_steps = sum(int(e.get("steps", 1)) for e in warm)
    out = {
        "measured_wall_ms": round(wall_ms, 3),
        "budget": budget,
        "budget_sum_ms": round(total, 3),
        "budget_gap_frac": round(abs(total - wall_ms) / wall_ms, 4)
        if wall_ms else 0.0,
        "within_tolerance": bool(
            wall_ms and abs(total - wall_ms) <= BUDGET_TOLERANCE * wall_ms),
        "shares": {k: round(v / wall_ms, 4) if wall_ms else 0.0
                   for k, v in budget.items()},
        "dispatches": len(steps), "steps": n_steps,
        "step_ms_warm_mean": round(warm_ms / warm_steps, 3)
        if warm_steps else None,
        "staging_overlapped_ms": round(
            max(0.0, stage_total_ms - staging_ms), 3),
    }
    out["top"], out["hints"] = _hints(out)
    return out


_HINTS = {
    "host_other_ms": "host-stall {pct}%: the device waits on the host "
                     "between dispatches — raise prefetch workers/depth "
                     "(`python -m paddle_tpu tune reader/prefetch`, "
                     "`tune executor/run_pipelined`) or move feed "
                     "building into the reader pipeline",
    "staging_ms": "staging {pct}%: device_put is not hidden behind "
                  "compute — raise prefetch_depth / steps_per_dispatch "
                  "(`python -m paddle_tpu tune executor/run_pipelined`)",
    "fetch_ms": "fetch-block {pct}%: the host blocks materializing "
                "fetches — jax dispatches asynchronously, so this bucket "
                "also absorbs device compute finishing under the "
                "materialization; trim fetch_list, fetch less often, or "
                "pass return_numpy=False and materialize lazily",
    "compile_ms": "compile {pct}%: set PADDLE_TPU_CACHE_DIR for warm "
                  "starts, or AOT-compile with Executor.compile() / "
                  "Trainer.train(warmup=True)",
    "compute_ms": "compute-bound {pct}%: the chip is the bottleneck — "
                  "tune device knobs (`python -m paddle_tpu tune "
                  "xla/scoped_vmem_limit_kib`) or shard "
                  "(`python -m paddle_tpu plan`)",
}


def _hints(report: dict, table: Optional[Dict[str, str]] = None):
    shares = report["shares"]
    table = table if table is not None else _HINTS
    top = max(shares, key=lambda k: shares[k])
    hints = []
    for k, share in sorted(shares.items(), key=lambda kv: -kv[1]):
        if share >= 0.15 or k == top:
            hints.append(table[k].format(pct=round(share * 100)))
    return top, hints


# ---------------------------------------------------------------------------
# request-time budget (serving path)
# ---------------------------------------------------------------------------
def serving_budget(events) -> Optional[dict]:
    """Per-request budget over ``serving/request`` + ``serving/batch``
    spans: queue+batch wait vs model dispatch.  None when the log has no
    completed request spans."""
    reqs = [e for e in events if e.get("kind") == "span"
            and e.get("name") == "serving/request"]
    if not reqs:
        return None
    batches = [e for e in events if e.get("kind") == "span"
               and e.get("name") == "serving/batch"]
    dispatch_by_req: Dict[object, float] = {}
    for b in batches:
        labels = b.get("labels") or {}
        dms = labels.get("dispatch_ms")
        if dms is None:
            continue
        for rid in labels.get("requests") or []:
            dispatch_by_req[rid] = float(dms)
    served = [e for e in reqs
              if (e.get("labels") or {}).get("status") == "ok"]
    # latency percentiles over SERVED requests only: under overload most
    # spans are sub-ms admission rejections, and folding those in would
    # report a tiny p50 for exactly the incident being diagnosed
    durs = sorted(float(e.get("dur_ms", 0.0))
                  for e in (served or reqs))
    n = len(durs)
    waits, disps = [], []
    for e in served:
        total = float(e.get("dur_ms", 0.0))
        rid = (e.get("labels") or {}).get("id")
        d = min(dispatch_by_req.get(rid, 0.0), total)
        disps.append(d)
        waits.append(total - d)
    mean = lambda xs: sum(xs) / len(xs) if xs else None   # noqa: E731
    out = {
        "requests": len(reqs), "served": len(served),
        "rejected": sum(1 for e in reqs
                        if (e.get("labels") or {}).get("status")
                        not in (None, "ok")),
        "request_ms_p50": round(durs[n // 2], 3),
        "request_ms_p99": round(durs[min(n - 1, int(n * 0.99))], 3),
        "budget": {
            "queue_wait_ms_mean": round(mean(waits), 3) if waits else None,
            "dispatch_ms_mean": round(mean(disps), 3) if disps else None,
        },
        "request_ms_mean": round(mean(
            [float(e.get("dur_ms", 0.0)) for e in served]), 3)
        if served else None,
        "batches": len(batches),
    }
    if served and out["budget"]["dispatch_ms_mean"] is not None:
        total = out["budget"]["queue_wait_ms_mean"] + \
            out["budget"]["dispatch_ms_mean"]
        mean_req = out["request_ms_mean"] or 0.0
        out["budget_sum_ms"] = round(total, 3)
        out["within_tolerance"] = bool(
            mean_req and abs(total - mean_req)
            <= BUDGET_TOLERANCE * mean_req)
        wait_share = (out["budget"]["queue_wait_ms_mean"] / mean_req
                      if mean_req else 0.0)
        out["top"] = ("queue_wait" if wait_share >= 0.5 else "dispatch")
        out["hints"] = [
            "queue wait {p}%: requests spend most of their latency "
            "waiting — raise max_batch / lower max_wait_ms (`python -m "
            "paddle_tpu tune serving/batcher`), add capacity, or lower "
            "queue_capacity to shed earlier".format(
                p=round(wait_share * 100))
        ] if out["top"] == "queue_wait" else [
            "dispatch {p}%: the model itself dominates — tune device "
            "knobs or shard the model".format(
                p=round(100 - wait_share * 100))
        ]
    return out


# ---------------------------------------------------------------------------
# token-step budget (incremental decode path)
# ---------------------------------------------------------------------------
def decode_budget(events) -> Optional[dict]:
    """Decode slot-pool budget over ``serving/decode_step`` spans: the
    batched token-step dispatch vs the scheduler gap around it, slot
    occupancy, and token throughput.  None when the log has no decode
    steps."""
    steps = [e for e in events if e.get("kind") == "span"
             and e.get("name") == "serving/decode_step"]
    if not steps:
        return None
    durs = sorted(float(e.get("dur_ms", 0.0)) for e in steps)
    n = len(durs)
    actives = [int((e.get("labels") or {}).get("active", 0))
               for e in steps]
    disps = [float((e.get("labels") or {}).get("dispatch_ms"))
             for e in steps
             if (e.get("labels") or {}).get("dispatch_ms") is not None]
    tokens = sum(actives)
    ts = [float(e["ts"]) for e in steps
          if isinstance(e.get("ts"), (int, float))]
    wall_s = (max(ts) - min(ts)) if len(ts) > 1 else 0.0
    mean = lambda xs: sum(xs) / len(xs) if xs else None   # noqa: E731
    out = {
        "steps": n, "tokens": tokens,
        "active_mean": round(mean(actives), 2),
        "step_ms_p50": round(durs[n // 2], 3),
        "step_ms_p99": round(durs[min(n - 1, int(n * 0.99))], 3),
        "dispatch_ms_mean": round(mean(disps), 3) if disps else None,
        "tokens_per_s": round(tokens / wall_s, 1) if wall_s > 0 else None,
    }
    if out["dispatch_ms_mean"] is not None and out["step_ms_p50"]:
        dispatch_share = min(1.0, out["dispatch_ms_mean"]
                             / max(mean(durs), 1e-9))
        out["top"] = ("dispatch" if dispatch_share >= 0.5 else "scheduler")
        out["hints"] = [
            "dispatch {p}%: the per-token-step model call dominates — "
            "more slots amortize it over more live sequences (`python -m "
            "paddle_tpu tune serving/decode_slots`)".format(
                p=round(dispatch_share * 100))
        ] if out["top"] == "dispatch" else [
            "scheduler {p}%: host-side admit/evict around the dispatch "
            "dominates — lower step_wait_ms or batch admissions".format(
                p=round(100 - dispatch_share * 100))
        ]
    return out


# ---------------------------------------------------------------------------
# remote sparse budget (pserver wire path)
# ---------------------------------------------------------------------------
_REMOTE_HINTS = {
    "client_wire_ms": "client-wire {pct}%: serialization + network + "
                      "pipelining dominate the remote sparse rounds — "
                      "batch more ids per round (dedup, bigger batches), "
                      "keep wire_mode='binary', and overlap rounds with "
                      "compute (SparseSession prefetch)",
    "server_queue_ms": "server-queue {pct}%: requests wait in a shard's "
                       "single-threaded serve loop before dispatch — the "
                       "shard is saturated: add pserver shards "
                       "(re-shard the id space) or split hot tables "
                       "across fleets",
    "server_kernel_ms": "server-kernel {pct}%: the shard's pull/push "
                        "kernels dominate — shrink the embedding dim, "
                        "use a cheaper optimizer slot layout, or spread "
                        "rows over more shards so each kernel touches "
                        "fewer",
}


def remote_budget(events) -> Optional[dict]:
    """Remote sparse pull/push budget over the client-side
    ``pserver/rpc`` spans: splits the measured client wall into
    **client-wire** (serialize + network + pipelined wait), **server-
    queue** (time a frame sat in a shard's serve loop before dispatch)
    and **server-kernel** (the shard's pull/push kernel), using the
    server timings each reply piggybacks (``srv_queue_ms`` /
    ``srv_kernel_ms`` labels — the slowest shard of the pipelined
    round, which is what the client actually waited on).  Components
    sum to the measured wall by construction (wire is the residual);
    None when the log carries no client rpc spans.

    Works from the TRAINER's log alone — the piggyback travels in the
    reply, so no shard log is needed for the split."""
    rpcs = [e for e in events if e.get("kind") == "span"
            and e.get("name") == "pserver/rpc"]
    client = [e for e in rpcs
              if (e.get("labels") or {}).get("side") != "server"]
    if not client:
        return None
    wall_ms = sum(float(e.get("dur_ms") or 0.0) for e in client)
    queue_ms = kernel_ms = 0.0
    attributed = 0
    by_op: Dict[str, int] = {}
    for e in client:
        labels = e.get("labels") or {}
        op = str(labels.get("op", "?"))
        by_op[op] = by_op.get(op, 0) + 1
        q, k = labels.get("srv_queue_ms"), labels.get("srv_kernel_ms")
        if q is None and k is None:
            continue
        attributed += 1
        queue_ms += float(q or 0.0)
        kernel_ms += float(k or 0.0)
    budget = {
        "client_wire_ms": round(max(0.0, wall_ms - queue_ms - kernel_ms),
                                3),
        "server_queue_ms": round(queue_ms, 3),
        "server_kernel_ms": round(kernel_ms, 3),
    }
    total = sum(budget.values())
    out = {
        "measured_wall_ms": round(wall_ms, 3),
        "budget": budget,
        "budget_sum_ms": round(total, 3),
        "budget_gap_frac": round(abs(total - wall_ms) / wall_ms, 4)
        if wall_ms else 0.0,
        "within_tolerance": bool(
            wall_ms and abs(total - wall_ms) <= BUDGET_TOLERANCE * wall_ms),
        "shares": {k: round(v / wall_ms, 4) if wall_ms else 0.0
                   for k, v in budget.items()},
        "rounds": len(client),
        "attributed_rounds": attributed,
        "by_op": by_op,
    }
    out["top"], out["hints"] = _hints(out, table=_REMOTE_HINTS)
    return out


# ---------------------------------------------------------------------------
# compiled-executable facts + static-model calibration
# ---------------------------------------------------------------------------
def executable_facts(step) -> Optional[dict]:
    """FLOPs / bytes / memory of a compiled step where this jax exposes
    them (``compat.executable_cost_analysis``); accepts a
    ``CompiledProgram``, a ``CachedStep``, or a raw jax ``Compiled``.
    None when unavailable (CPU stubs, API drift) — callers fall back to
    the static model."""
    from .. import compat
    for obj in (step, getattr(step, "_step", None),
                getattr(step, "_compiled", None)):
        if obj is None:
            continue
        cost = compat.executable_cost_analysis(obj)
        mem = compat.executable_memory_analysis(obj)
        if cost or mem:
            out = {"source": "cost_analysis"}
            if cost:
                out.update({k: cost[k] for k in
                            ("flops", "bytes_accessed",
                             "transcendentals") if k in cost})
            if mem:
                out["memory"] = mem
            return out
    return None


def calibration_row(program, measured_step_ms: float,
                    mesh_axes: Optional[Dict[str, int]] = None,
                    assume_batch: int = 64,
                    facts: Optional[dict] = None) -> dict:
    """One calibration-table row: the PR 7 static model's predicted step
    time vs a measured one, plus the stored ratio the planner can fold
    into its nominal constants later (ROADMAP item 2).

    ``ratio > 1``: the model is optimistic for this program class (real
    steps are slower than the proxy); ``< 1``: pessimistic.  Ratios are
    per-program-digest, so re-doctoring the same program overwrites its
    row instead of accumulating duplicates."""
    from ..analysis.cost_model import estimate_cost
    from ..core import compile_cache
    report = estimate_cost(program, mesh_axes or {},
                           assume_batch=assume_batch)
    predicted_ms = report.step_time_proxy_s * 1e3
    digest = compile_cache.fingerprint_hex(
        compile_cache.program_content_digest(program))[:16]
    row = {
        "program": digest,
        "assume_batch": int(assume_batch),
        "mesh_axes": dict(mesh_axes or {}),
        "predicted_ms": round(predicted_ms, 6),
        "measured_ms": round(float(measured_step_ms), 6),
        "ratio": round(float(measured_step_ms) / predicted_ms, 4)
        if predicted_ms > 0 else None,
        "model": "static" if facts is None else "static+cost_analysis",
    }
    if facts:
        row["executable"] = facts
    return row


def _read_calibration_doc(path: str) -> dict:
    """Existing table -> {"programs": {...}, "op_classes": {...}}
    (tolerates the PR 10 format-1 layout and a bare programs map)."""
    import json
    programs: Dict[str, dict] = {}
    op_classes: Dict[str, dict] = {}
    try:
        with open(path) as f:
            prev = json.load(f)
        if isinstance(prev, dict):
            p = prev.get("programs", prev)
            if isinstance(p, dict):
                programs.update(p)
            if isinstance(prev.get("op_classes"), dict):
                op_classes.update(prev["op_classes"])
    except (OSError, ValueError):
        pass   # first write, or an unreadable table: start fresh
    return {"programs": programs, "op_classes": op_classes}


def _write_calibration_doc(doc: dict, path: str) -> dict:
    import json
    import os
    out = {"format": 2, "programs": doc["programs"]}
    if doc.get("op_classes"):
        out["op_classes"] = doc["op_classes"]
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(out, f, indent=1, sort_keys=True)
    os.replace(tmp, path)
    return out


def save_calibration(rows: List[dict], path: str) -> dict:
    """Merge per-program calibration rows into a JSON table keyed by
    program digest (atomic rewrite, op-class rows preserved); returns
    the merged table."""
    doc = _read_calibration_doc(path)
    for row in rows:
        doc["programs"][row["program"]] = row
    return _write_calibration_doc(doc, path)


def save_op_class_calibration(rows: List[dict], path: str) -> dict:
    """Merge per-op-CLASS rows (``opprof.op_class_rows`` output — the
    calibration_row schema extended with ``op_type``) into the same
    table under ``op_classes``, keyed ``<digest>:<op_type>`` so
    re-profiling a program overwrites its classes instead of
    accumulating duplicates.  The per-program rows are preserved —
    one file carries both granularities for the planner."""
    doc = _read_calibration_doc(path)
    for row in rows:
        doc["op_classes"][f"{row['program']}:{row['op_type']}"] = row
    return _write_calibration_doc(doc, path)


def load_op_class_ratios(table) -> Dict[str, float]:
    """Per-op-TYPE correction ratios for the planner
    (``analysis.planner.plan(op_class_ratios=...)``): the MEDIAN
    measured/predicted ratio per op type across every program in the
    table's ``op_classes`` section.  ``table`` is a path or an
    already-loaded dict; {} when the table has no op-class rows (the
    planner then ranks on the uncorrected nominal constants)."""
    import json
    import statistics
    if isinstance(table, (str, bytes)) or hasattr(table, "__fspath__"):
        with open(table) as f:
            table = json.load(f)
    if not isinstance(table, dict):
        raise ValueError("calibration table must be a JSON object")
    by_type: Dict[str, List[float]] = {}
    for row in (table.get("op_classes") or {}).values():
        if not isinstance(row, dict) or "op_type" not in row:
            continue   # foreign/hand-edited rows must not crash the load
        r = row.get("ratio")
        if isinstance(r, (int, float)) and r > 0:
            by_type.setdefault(str(row["op_type"]), []).append(float(r))
    return {t: float(statistics.median(rs))
            for t, rs in sorted(by_type.items())}


# ---------------------------------------------------------------------------
# the doctor report
# ---------------------------------------------------------------------------
def doctor_report(paths, program=None, assume_batch: int = 64,
                  mesh_axes: Optional[Dict[str, int]] = None) -> dict:
    """Full doctor document for one (possibly multi-file) log: training
    step budget, serving request budget, span latency stats, and — when
    a program is supplied — the cost-model calibration row."""
    from . import tracing
    from .export import iter_log_events
    events, files = iter_log_events(paths)
    out: dict = {"files": files}
    tb = step_budget(events)
    if tb is not None:
        out["training"] = tb
    sb = serving_budget(events)
    if sb is not None:
        out["serving"] = sb
    db = decode_budget(events)
    if db is not None:
        out["decode"] = db
    rb = remote_budget(events)
    if rb is not None:
        out["remote"] = rb
    stats = tracing.span_stats(events)
    if stats:
        out["span_stats"] = stats
    if program is not None and tb is not None \
            and tb.get("step_ms_warm_mean"):
        out["calibration"] = calibration_row(
            program, tb["step_ms_warm_mean"], mesh_axes=mesh_axes,
            assume_batch=assume_batch)
    tops = [s.get("top") for s in (out.get("training"),
                                   out.get("serving"),
                                   out.get("remote")) if s]
    if tops:
        out["top_bottleneck"] = tops[0]
    return out


def render_doctor(report: dict) -> str:
    """Human-readable doctor rendering."""
    from .export import source_label
    lines: List[str] = []
    files = report.get("files") or []
    if len(files) > 1:
        # a merged fleet log: name which process each file came from
        for f in files:
            lines.append(f"source [{source_label(f)}]: {f['file']} "
                         f"({f['events']} event(s))")
    tb = report.get("training")
    if tb:
        lines.append(
            f"training: {tb['steps']} step(s) in {tb['dispatches']} "
            f"dispatch(es), measured wall {tb['measured_wall_ms']} ms "
            f"(budget sum {tb['budget_sum_ms']} ms, "
            f"gap {round(tb['budget_gap_frac'] * 100, 2)}%"
            + ("" if tb["within_tolerance"] else " — OVER TOLERANCE")
            + ")")
        for k, v in sorted(tb["budget"].items(),
                           key=lambda kv: -kv[1]):
            lines.append(f"  {k[:-3]:>12}: {v:12.3f} ms  "
                         f"({round(tb['shares'][k] * 100, 1)}%)")
        if tb.get("staging_overlapped_ms"):
            lines.append(f"  (+ {tb['staging_overlapped_ms']} ms staging "
                         f"overlapped with compute — already free)")
        for h in tb["hints"]:
            lines.append(f"  hint: {h}")
    sb = report.get("serving")
    if sb:
        lines.append(
            f"serving: {sb['served']}/{sb['requests']} request(s) "
            f"served, p50 {sb['request_ms_p50']} ms, "
            f"p99 {sb['request_ms_p99']} ms")
        b = sb["budget"]
        if b.get("dispatch_ms_mean") is not None:
            lines.append(f"  queue+batch wait mean: "
                         f"{b['queue_wait_ms_mean']} ms; model dispatch "
                         f"mean: {b['dispatch_ms_mean']} ms")
        for h in sb.get("hints", []):
            lines.append(f"  hint: {h}")
    db = report.get("decode")
    if db:
        lines.append(
            f"decode: {db['tokens']} token(s) in {db['steps']} "
            f"step(s), mean active {db['active_mean']}, step p50 "
            f"{db['step_ms_p50']} ms, p99 {db['step_ms_p99']} ms"
            + (f", {db['tokens_per_s']} tokens/s"
               if db.get("tokens_per_s") is not None else ""))
        if db.get("dispatch_ms_mean") is not None:
            lines.append(f"  step dispatch mean: "
                         f"{db['dispatch_ms_mean']} ms")
        for h in db.get("hints", []):
            lines.append(f"  hint: {h}")
    rb = report.get("remote")
    if rb:
        lines.append(
            f"remote sparse: {rb['rounds']} rpc round(s) "
            f"({rb['attributed_rounds']} with server timings), measured "
            f"wall {rb['measured_wall_ms']} ms (budget sum "
            f"{rb['budget_sum_ms']} ms, gap "
            f"{round(rb['budget_gap_frac'] * 100, 2)}%"
            + ("" if rb["within_tolerance"] else " — OVER TOLERANCE")
            + ")")
        for k, v in sorted(rb["budget"].items(), key=lambda kv: -kv[1]):
            lines.append(f"  {k[:-3]:>16}: {v:12.3f} ms  "
                         f"({round(rb['shares'][k] * 100, 1)}%)")
        for h in rb["hints"]:
            lines.append(f"  hint: {h}")
    cal = report.get("calibration")
    if cal:
        lines.append(
            f"calibration: program {cal['program']} predicted "
            f"{cal['predicted_ms']} ms vs measured {cal['measured_ms']} "
            f"ms -> ratio {cal['ratio']} (static-model correction "
            f"factor; stored per program digest)")
    if not any(report.get(k) for k in
               ("training", "serving", "decode", "remote", "calibration")):
        lines.append("doctor: no step events or request spans in this "
                     "log — run with observe on and a metrics_log set")
    elif report.get("top_bottleneck"):
        lines.insert(0, f"top bottleneck: {report['top_bottleneck']}")
    return "\n".join(lines)
