"""Per-op runtime profiler + HBM timeline: the op-granular measurement
layer under the PR 10 step-level doctor.

``doctor`` decomposes a step into compute/fetch/staging/compile buckets;
this module answers the next question — **which op** — with three joined
views over the Program IR (the REGISTER_TIMER/globalStat per-layer-timer
capability of the reference, exceeded to per-op measured-vs-modeled):

* **Measured** — an eager per-op replay of one step via
  ``core.executor.run_op`` with ``jax.block_until_ready`` host timers and
  warmup-discarded repeated windows (``tuning.search.time_windows``),
  replicating the compiled step's input dtype coercion exactly as the
  NaN bisect does (``nanprov.make_eager_context``), so every op times at
  the precision the compiled step computes at.  Ops are walked in
  EXECUTION order — forward slice, the ``backward`` pseudo-op (one
  ``value_and_grad`` unit producing every ``@GRAD``), then the optimizer
  update ops — with per-op RNG keys aligned to the compiled trace
  (``ctx._op_uid`` reset per window, and to 0 before the backward, which
  is where the compiled step's forward uids start).
* **Modeled** — each measured op joined against the PR 7 static cost
  model's per-op FLOPs/HBM estimates (``analysis.cost_model``):
  predicted-vs-measured ratios, a roofline verdict per op
  (compute-bound vs memory-bound under the nominal constants), a
  per-op-TYPE calibration table extending the PR 10 ``calibration_row``
  format (keyed program digest + op type — what
  ``analysis.planner.plan(op_class_ratios=...)`` consumes instead of one
  program-wide scalar), and a ranked **XLA-loses-here** report naming
  the pre-registered Pallas candidates (``pallas/fused_optimizer_update``,
  ``pallas/lod_gather_scatter``) when their op classes dominate.
* **Memory timeline** — the liveness walk emitting a per-op live-bytes
  curve from the MEASURED array sizes of the replay, the peak position
  vs the cost model's per-device peak-HBM estimate, and (opt-in) the
  compiled executable's ``memory_analysis`` as the compiled-side
  cross-check (``compat.executable_memory_analysis`` — None where this
  jax hides it).

Surfaces: ``python -m paddle_tpu profile prog.json`` /
``doctor --per-op`` (cli.py) and ``benchmark/opprof.py``.

This module is imported LAZILY only (profile/doctor CLI branches, the
benchmark driver) — it pulls ``analysis.cost_model`` and
``tuning.search``, which the training hot path must never pay for
(repo-lint enforced, like ``attribution``).  Profiling is an offline
tool: it never touches compile fingerprints or the executor's step
cache, so ``Executor.run``/``run_steps`` stay byte-identical with it
loaded (tier-1 counter-delta + retrace_guard).
"""
from __future__ import annotations

import logging
from typing import Dict, List, Optional

from . import metrics as _metrics
from . import tracing as _tracing

logger = logging.getLogger("paddle_tpu")

__all__ = [
    "TOLERANCE", "PALLAS_CANDIDATES", "synth_feeds", "synth_state",
    "profile_program", "render_profile", "op_class_rows",
]

# Per-op measured table must sum to the eager-replay total within this
# fraction — pinned equal to attribution.BUDGET_TOLERANCE by tier-1
# (tests/test_opprof.py), kept a separate literal so loading the
# profiler never pulls the attribution/cost-model import chain early.
TOLERANCE = 0.15

# ROADMAP item 5's Pallas expansion candidates: op classes whose
# domination in a measured profile names a pre-registered tunable (the
# decision-rule IDs registered beside ops/optimizer_ops.py and
# ops/sequence_ops.py).  The optimizer family is pure memory traffic
# (one fused kernel over all param leaves is the candidate); the lod
# sequence family is gather/scatter over padded [B, T, ...] layouts.
_OPTIMIZER_OPS = frozenset((
    "sgd", "momentum", "adam", "adamax", "adagrad", "adadelta",
    "decayed_adagrad", "rmsprop", "ftrl", "proximal_gd",
    "proximal_adagrad"))
_LOD_SEQUENCE_OPS = frozenset((
    "sequence_pool", "sequence_softmax", "sequence_expand",
    "sequence_expand_as", "sequence_concat", "sequence_slice",
    "sequence_pad", "sequence_unpad", "sequence_reshape",
    "sequence_reverse", "lod_reset", "sub_nested_seq"))
PALLAS_CANDIDATES: Dict[str, str] = {
    **{t: "pallas/fused_optimizer_update" for t in _OPTIMIZER_OPS},
    **{t: "pallas/lod_gather_scatter" for t in _LOD_SEQUENCE_OPS},
}


# ---------------------------------------------------------------------------
# Feed/state synthesis (profiling a serialized prog.json needs values)
# ---------------------------------------------------------------------------
def _data_vars(program):
    out = []
    for b in program.blocks:
        for v in b.vars.values():
            if getattr(v, "is_data", False):
                out.append(v)
    return out


def _int_feed_bounds(program) -> Dict[str, int]:
    """Upper bounds for synthesized integer feeds, from their direct
    consumers: lookup_table ids must stay under the table's rows,
    cross_entropy labels under the logit width.  Anything else gets the
    conservative default (2)."""
    gb = program.global_block()
    bounds: Dict[str, int] = {}

    def dim(name, idx):
        v = gb._find_var_recursive(name)
        if v is None or v.shape is None or len(v.shape) <= idx:
            return None
        d = v.shape[idx]
        return int(d) if d and d > 0 else None

    for b in program.blocks:
        for op in b.ops:
            if op.type == "lookup_table":
                ws = op.inputs.get("W", [])
                vocab = dim(ws[0], 0) if ws else None
                if vocab:
                    for n in op.inputs.get("Ids", []):
                        bounds[n] = min(bounds.get(n, vocab), vocab)
            elif op.type in ("cross_entropy", "one_hot"):
                xs = op.inputs.get("X", [])
                classes = dim(xs[0], -1) if xs else None
                if classes:
                    for n in op.inputs.get("Label", []):
                        bounds[n] = min(bounds.get(n, classes), classes)
    return bounds


def synth_feeds(program, batch: int = 64, seq_len: int = 8,
                seed: int = 0) -> Dict[str, object]:
    """Seeded random feeds shaped from the program's data vars (the
    fake-data-provider role, for profiling a serialized program without
    its reader): floats ~ U[0,1), ints bounded by their consumers
    (:func:`_int_feed_bounds`), ``-1`` dims resolved to ``batch``
    (leading) / ``seq_len`` (sequence dims), with ``@LEN`` companions
    for ``lod_level`` > 0 vars."""
    import numpy as np
    rng = np.random.RandomState(seed)
    bounds = _int_feed_bounds(program)
    feeds: Dict[str, object] = {}
    for v in _data_vars(program):
        shape = list(v.shape if v.shape is not None else (-1,))
        dims = []
        for i, d in enumerate(shape):
            if d is None or int(d) < 0:
                dims.append(batch if i == 0 else seq_len)
            else:
                dims.append(int(d))
        if not dims:
            dims = [batch]
        dt = np.dtype(v.dtype) if v.dtype is not None else np.dtype("f4")
        if dt.kind in "iu":
            hi = max(2, int(bounds.get(v.name, 2)))
            feeds[v.name] = rng.randint(0, hi, size=dims).astype(dt)
        elif dt.kind == "b":
            feeds[v.name] = np.zeros(dims, dtype=dt)
        else:
            feeds[v.name] = rng.rand(*dims).astype(dt)
        lod = int(getattr(v, "lod_level", 0) or 0)
        if lod >= 1:
            t = dims[1] if len(dims) > 1 else seq_len
            feeds[v.name + "@LEN"] = np.full((dims[0],), t, dtype="int64")
        if lod >= 2 and len(dims) > 2:
            feeds[v.name + "@LEN2"] = np.full(
                (dims[0], dims[1]), dims[2], dtype="int64")
    return feeds


def synth_state(program, scope=None, batch: int = 64,
                seed: int = 0) -> Dict[str, object]:
    """Values for every persistable var the program references: the live
    ``scope`` value when present (a startup-initialized run profiles its
    real parameters), else a seeded synthetic — small positive uniforms,
    so learning rates / beta-pow accumulators stay in a sane range."""
    import numpy as np
    rng = np.random.RandomState(seed + 1)
    referenced = set()
    for b in program.blocks:
        for op in b.ops:
            referenced.update(op.input_names)
            referenced.update(op.output_names)
            referenced.update(op.attrs.get("params", ())
                              if op.type == "backward" else ())
    out: Dict[str, object] = {}
    for b in program.blocks:
        for v in b.vars.values():
            if not v.persistable or v.name in out \
                    or v.name not in referenced:
                continue
            if scope is not None and scope.has(v.name):
                out[v.name] = scope.get(v.name)
                continue
            shape = tuple(batch if (d is None or int(d) < 0) else int(d)
                          for d in (v.shape if v.shape is not None
                                    else (1,)))
            dt = np.dtype(v.dtype) if v.dtype is not None \
                else np.dtype("f4")
            if dt.kind == "f":
                out[v.name] = rng.uniform(0.01, 0.1, shape).astype(dt)
            else:
                out[v.name] = np.zeros(shape, dtype=dt)
    return out


# ---------------------------------------------------------------------------
# The measured walk
# ---------------------------------------------------------------------------
def _measure_windows(call, *, reps: int, warmup: int) -> dict:
    # the shared measurement harness: median of `reps` windows after
    # `warmup` discarded ones (compiles, cache warming)
    from ..tuning.search import time_windows
    return time_windows(call, reps=reps, warmup=warmup)


def _bw_out_names(op) -> List[str]:
    from ..core.program import grad_var_name
    names = [grad_var_name(p) for p in op.attrs.get("params", ())]
    loss = op.attrs.get("loss")
    if loss:
        names.append(loss)
    return names


def profile_program(program, *, executor=None, feed=None, state=None,
                    scope=None, batch: int = 64, seq_len: int = 8,
                    step: int = 0, is_test: bool = False, reps: int = 2,
                    warmup: int = 1, top: int = 10,
                    mesh_axes: Optional[Dict[str, int]] = None,
                    fetch_list=None, compiled_check: bool = False,
                    measure=None) -> dict:
    """Profile one step of ``program`` op by op; returns the joined
    measured/modeled/memory report (JSON-serializable except an optional
    ``fetches`` entry when ``fetch_list`` names vars to materialize —
    the dtype/value-parity hook).

    ``measure(call, reps=, warmup=)`` must run ``call`` at least once
    and return the ``time_windows`` dict — injectable so the test
    suite's fake-timer matrix exercises the whole join deterministically.
    The call order is frozen: one measurement per op in execution order,
    then ONE measurement of the full replay (the eager total the per-op
    table must sum to within :data:`TOLERANCE`)."""
    import jax
    import numpy as np

    from ..core import compile_cache
    from ..core.executor import (Env, LoweringContext, _run_backward,
                                 _to_bf16, run_op)
    from .nanprov import make_eager_context

    if executor is None:
        from ..core.executor import Executor
        executor = Executor()
    if scope is None:
        from ..core.scope import global_scope
        scope = global_scope()

    gb = program.global_block()
    feed_arrays = dict(feed) if feed is not None \
        else synth_feeds(program, batch=batch, seq_len=seq_len)
    # the same declared-dtype coercion Executor.run applies to feeds
    for name, val in list(feed_arrays.items()):
        arr = val if isinstance(val, jax.Array) else np.asarray(val)
        if gb.has_var(name):
            want = jax.dtypes.canonicalize_dtype(gb.var(name).dtype)
            if arr.dtype != want:
                arr = arr.astype(want)
        feed_arrays[name] = arr
    if state is None:
        state = synth_state(program, scope=scope, batch=batch)

    env, ctx, bw_idx = make_eager_context(
        executor, program, feed_arrays, state, step, is_test)
    initial = dict(env.local)
    # AMP TRAINING precision parity: the compiled step runs every
    # forward op in bf16 INSIDE value_and_grad (the leaves cast down,
    # fp32 grads cast back out — executor._run_backward's recipe), so
    # the walk measures forward ops against a bf16 shadow env while the
    # backward/update ops keep the fp32 master-weight env (pure-
    # inference AMP needs no shadow: make_eager_context already cast
    # the whole env down)
    amp_train = bool(executor.amp) and bw_idx is not None
    fwd_env = None
    if amp_train:
        fwd_env = Env(gb)
        fwd_env.local.update({k: _to_bf16(v) for k, v in initial.items()})
    measure = measure or _measure_windows
    _metrics.inc_counter("opprof/runs")

    ops = gb.ops
    op_out_names: List[List[str]] = [
        _bw_out_names(op) if i == bw_idx
        else [n for names in op.outputs.values() for n in names]
        for i, op in enumerate(ops)]

    rows: List[dict] = []
    sizes: Dict[str, int] = {
        n: int(getattr(v, "nbytes", 0)) for n, v in env.local.items()}
    for idx, op in enumerate(ops):
        is_bw = idx == bw_idx
        # forward ops of an AMP training step time against the bf16
        # shadow; everything else against the fp32 env
        tenv = fwd_env if (fwd_env is not None and idx < bw_idx) else env
        # RNG parity with the compiled trace: inside the compiled step
        # the forward ops run INSIDE value_and_grad with uids starting
        # at 0, so the backward replays from uid 0; every other op
        # re-runs from the uid it first executed at
        uid0 = 0 if is_bw else ctx._op_uid
        out_names = op_out_names[idx]
        aliases: Dict[str, object] = {}
        if not is_bw:
            for n in set(op.input_names) & set(op.output_names):
                if tenv.has(n):
                    aliases[n] = tenv.get(n)
        if is_bw:
            in_names = list(op.attrs.get("params", ()))
        else:
            in_names = list(op.input_names)
        in_bytes = sum(int(getattr(tenv.get(n), "nbytes", 0))
                       for n in in_names if tenv.has(n))

        def call(op=op, uid0=uid0, aliases=aliases, is_bw=is_bw,
                 out_names=out_names, tenv=tenv):
            ctx._op_uid = uid0
            # in-place consumers (optimizer updates write their Param
            # input): restore the pre-op value so repeated windows run
            # the identical computation
            for n, val in aliases.items():
                tenv.set(n, val)
            if is_bw:
                _run_backward(ops[:bw_idx], op, env, ctx)
            else:
                run_op(op, tenv, ctx)
            jax.block_until_ready(
                [tenv.get(n) for n in out_names if tenv.has(n)])

        with _tracing.span("opprof/op", op_type=op.type, index=idx):
            w = measure(call, reps=reps, warmup=warmup)
        wall_ms = float(w["seconds"]) * 1e3
        _metrics.inc_counter("opprof/ops")
        _metrics.observe_hist("opprof/op_ms", wall_ms)
        out_bytes = 0
        out_shapes, out_dtypes = [], []
        for n in out_names:
            if not tenv.has(n):
                continue
            v = tenv.get(n)
            sizes[n] = int(getattr(v, "nbytes", 0))
            out_bytes += sizes[n]
            out_shapes.append(list(getattr(v, "shape", ())))
            out_dtypes.append(str(getattr(v, "dtype", "?")))
        phase = ("backward" if is_bw else
                 "forward" if bw_idx is None or idx < bw_idx else
                 "update")
        rows.append({
            "index": idx, "op_type": op.type, "phase": phase,
            "wall_ms": round(wall_ms, 6),
            "windows_ms": [round(t * 1e3, 6) for t in w.get("windows", ())],
            "spread_pct": w.get("spread_pct", 0.0),
            "bytes": int(in_bytes + out_bytes),
            "out_shapes": out_shapes, "out_dtypes": out_dtypes,
        })

    # -- eager total: one full replay measured end to end (same blocking
    #    discipline as the per-op windows, so the table can sum to it)
    def total_call():
        import jax as _jax
        env2 = Env(gb)
        env2.local.update(initial)
        fenv2 = None
        if amp_train:
            fenv2 = Env(gb)
            fenv2.local.update(
                {k: _to_bf16(v) for k, v in initial.items()})
        ctx2 = LoweringContext(
            program, ctx.base_key, is_test=is_test, amp=executor.amp,
            mesh=getattr(executor, "mesh", None),
            compute_dtype=executor.compute_dtype,
            conv1x1_pallas=executor.conv1x1_pallas)
        for i, op in enumerate(ops):
            # same per-op env discipline as the measured walk, so the
            # per-op table can sum to this total
            tenv2 = fenv2 if (fenv2 is not None and i < bw_idx) else env2
            if i == bw_idx:
                ctx2._op_uid = 0
                _run_backward(ops[:bw_idx], op, env2, ctx2)
            else:
                run_op(op, tenv2, ctx2)
            _jax.block_until_ready(
                [tenv2.get(n) for n in op_out_names[i] if tenv2.has(n)])

    tw = measure(total_call, reps=reps, warmup=warmup)
    eager_total_ms = float(tw["seconds"]) * 1e3
    per_op_sum_ms = sum(r["wall_ms"] for r in rows)
    gap = (abs(per_op_sum_ms - eager_total_ms) / eager_total_ms
           if eager_total_ms > 0 else 0.0)

    # -- modeled join + per-op-class calibration + XLA-loses-here
    digest = compile_cache.fingerprint_hex(
        compile_cache.program_content_digest(program))[:16]
    cost = _join_modeled(program, rows, mesh_axes, batch)
    report: dict = {
        "program": digest, "batch": int(batch),
        "mesh_axes": dict(mesh_axes or {}),
        "reps": int(reps), "warmup": int(warmup),
        "ops": len(rows),
        "eager_total_ms": round(eager_total_ms, 6),
        "per_op_sum_ms": round(per_op_sum_ms, 6),
        "sum_gap_frac": round(gap, 4),
        "within_tolerance": bool(gap <= TOLERANCE),
        "tolerance": TOLERANCE,
        "rows": rows,
        "top": sorted(rows, key=lambda r: -r["wall_ms"])[:max(1, top)],
        "op_classes": op_class_rows(rows, digest, batch, mesh_axes),
        "xla_loses_here": _xla_loses_here(rows, per_op_sum_ms, top),
        "memory": _memory_view(program, sizes, bw_idx, mesh_axes, batch,
                               cost=cost),
    }
    if compiled_check:
        report["memory"]["executable"] = _compiled_facts(
            executor, program, feed_arrays, state, is_test)
    if fetch_list:
        report["fetches"] = {
            str(n): np.asarray(env.get(str(n))) for n in fetch_list
            if env.has(str(n))}
    return report


# ---------------------------------------------------------------------------
# Modeled join
# ---------------------------------------------------------------------------
def _join_modeled(program, rows, mesh_axes, assume_batch):
    from ..analysis.cost_model import (HBM_GBPS, ICI_GBPS, PEAK_FLOPS,
                                       estimate_cost)
    try:
        cost = estimate_cost(program, mesh_axes or {},
                             assume_batch=assume_batch)
    except Exception as e:
        # a program the static model cannot walk still profiles measured-
        # only; the join is best-effort by design
        logger.warning("opprof: static cost model failed (%s: %s); "
                       "measured-only profile", type(e).__name__, e)
        return None
    by_idx = {c.loc[1]: c for c in cost.op_costs if c.loc[0] == 0}
    for row in rows:
        c = by_idx.get(row["index"])
        if c is None:
            continue
        compute_s = c.flops / PEAK_FLOPS
        hbm_s = c.bytes / HBM_GBPS
        pred_ms = (compute_s + hbm_s
                   + c.collective_bytes / ICI_GBPS) * 1e3
        row["modeled"] = {
            "flops": c.flops, "hbm_bytes": c.bytes,
            "predicted_ms": round(pred_ms, 9),
            "roofline": ("compute-bound" if compute_s >= hbm_s
                         else "memory-bound"),
            "arithmetic_intensity": round(c.flops / c.bytes, 4)
            if c.bytes else None,
        }
        row["ratio"] = round(row["wall_ms"] / pred_ms, 4) \
            if pred_ms > 0 else None
    return cost


def _agg_by_type(rows) -> Dict[str, dict]:
    """One accumulation pass shared by the calibration table and the
    XLA-loses-here ranking: per op TYPE, measured/count over ALL rows
    plus the measured/predicted pair over the MODELED subset (only
    modeled rows can calibrate — a measured-only row has no ratio)."""
    agg: Dict[str, dict] = {}
    for row in rows:
        a = agg.setdefault(row["op_type"], {
            "measured_ms": 0.0, "count": 0, "modeled_measured_ms": 0.0,
            "modeled_predicted_ms": 0.0, "modeled_count": 0})
        a["measured_ms"] += row["wall_ms"]
        a["count"] += 1
        m = row.get("modeled")
        if m and m.get("predicted_ms"):
            a["modeled_measured_ms"] += row["wall_ms"]
            a["modeled_predicted_ms"] += m["predicted_ms"]
            a["modeled_count"] += 1
    return agg


def op_class_rows(rows, digest: str, assume_batch: int,
                  mesh_axes: Optional[Dict[str, int]]) -> List[dict]:
    """Aggregate per-op measured/predicted into one calibration row per
    op TYPE — the PR 10 ``calibration_row`` schema extended with the op
    class key, merged into the same table by
    ``attribution.save_op_class_calibration`` and consumed by
    ``analysis.planner.plan(op_class_ratios=...)``."""
    agg = _agg_by_type(rows)
    out = []
    for op_type in sorted(agg):
        a = agg[op_type]
        if not a["modeled_count"]:
            continue
        out.append({
            "program": digest, "op_type": op_type,
            "predicted_ms": round(a["modeled_predicted_ms"], 6),
            "measured_ms": round(a["modeled_measured_ms"], 6),
            "ratio": round(a["modeled_measured_ms"]
                           / a["modeled_predicted_ms"], 4)
            if a["modeled_predicted_ms"] > 0 else None,
            "count": a["modeled_count"],
            "assume_batch": int(assume_batch),
            "mesh_axes": dict(mesh_axes or {}),
            "model": "static-per-op",
        })
    return out


def _xla_loses_here(rows, total_ms: float, top: int) -> List[dict]:
    """Ranked where-the-time-goes by op class, each entry carrying the
    pre-registered Pallas-candidate tunable + decision rule when its
    class is one (ROADMAP item 5's 'grow Pallas coverage where
    attribution data says XLA underperforms' now has a committed,
    ranked answer)."""
    from ..core.registry import get_tunable, has_tunable
    agg = {t: {"measured_ms": a["measured_ms"], "count": a["count"],
               "predicted_ms": a["modeled_predicted_ms"]}
           for t, a in _agg_by_type(rows).items()}
    ranked = []
    for op_type, a in sorted(agg.items(),
                             key=lambda kv: -kv[1]["measured_ms"]):
        entry = {
            "op_type": op_type, "count": a["count"],
            "measured_ms": round(a["measured_ms"], 6),
            "share": round(a["measured_ms"] / total_ms, 4)
            if total_ms > 0 else 0.0,
            "predicted_ms": round(a["predicted_ms"], 6),
            "ratio": round(a["measured_ms"] / a["predicted_ms"], 4)
            if a["predicted_ms"] > 0 else None,
        }
        cand = PALLAS_CANDIDATES.get(op_type)
        if cand:
            entry["pallas_candidate"] = cand
            if has_tunable(cand):
                t = get_tunable(cand)
                entry["decision_rule"] = t["decision_rule"]
                entry["pending_hardware"] = t["pending_hardware"]
        ranked.append(entry)
    return ranked[:max(1, top)]


# ---------------------------------------------------------------------------
# Memory timeline
# ---------------------------------------------------------------------------
def _memory_view(program, sizes: Dict[str, int], bw_idx,
                 mesh_axes, assume_batch, cost=None) -> dict:
    """Per-op live-bytes curve from the MEASURED array sizes, using the
    cost model's liveness rules (a var lives producer -> last consumer;
    forward activations pin to the backward — XLA holds them for the
    VJP), plus the static model's per-device peak estimate alongside
    (read from ``cost``, the modeled join's CostReport; None when the
    static model could not walk this program — re-estimating here would
    only re-raise what the join already swallowed)."""
    gb = program.global_block()
    persistable = {v.name for b in program.blocks
                   for v in b.vars.values() if v.persistable}
    state_bytes = sum(sizes.get(n, 0) for n in persistable)

    def outs(i, op):
        if i == bw_idx:
            return _bw_out_names(op)
        return [n for names in op.outputs.values() for n in names]

    last_use: Dict[str, int] = {}
    produced_at: Dict[str, int] = {}
    for i, op in enumerate(gb.ops):
        for n in op.input_names:
            last_use[n] = i
        if i == bw_idx:
            for n in op.attrs.get("params", ()):
                last_use[n] = max(last_use.get(n, i), i)
    for i, op in enumerate(gb.ops):
        for n in outs(i, op):
            produced_at.setdefault(n, i)
    if bw_idx is not None:
        for n, born in produced_at.items():
            if born < bw_idx and n not in persistable:
                last_use[n] = max(last_use.get(n, born), bw_idx)

    live: Dict[str, int] = {}
    curve: List[dict] = []
    peak, peak_idx = 0, 0
    for i, op in enumerate(gb.ops):
        for n in outs(i, op):
            if n not in persistable and n not in live:
                live[n] = sizes.get(n, 0)
        cur = state_bytes + sum(live.values())
        if cur > peak:
            peak, peak_idx = cur, i
        curve.append({"index": i, "op_type": op.type,
                      "live_bytes": int(cur)})
        for n in [n for n in live if last_use.get(n, i) <= i]:
            del live[n]

    modeled = cost.peak_hbm_bytes_per_device if cost is not None else None
    out = {
        "timeline": curve,
        "state_bytes": int(state_bytes),
        "peak_bytes": int(peak), "peak_index": peak_idx,
        "peak_op": gb.ops[peak_idx].type if gb.ops else None,
        "modeled_peak_bytes": round(modeled, 1)
        if modeled is not None else None,
    }
    if modeled:
        out["peak_ratio"] = round(peak / modeled, 4)
    return out


def _compiled_facts(executor, program, feed_arrays, state, is_test):
    """Compiled-side cross-check: AOT-compile this step into a THROWAWAY
    executor + scope and read cost/memory analysis where this jax
    exposes them (``compat.executable_cost_analysis``/``_memory_analysis``
    via ``attribution.executable_facts``).  The throwaway executor keeps
    the module's zero-touch invariant: compiling through the caller's
    executor would install a step in ITS cache and bump ITS compile
    counters.  None on any failure — the profile is eager-first by
    design, and a backend without the API must not take the measured
    views down with it."""
    try:
        from ..core.executor import Executor
        from ..core.scope import Scope
        sc = Scope()
        for k, v in state.items():
            sc.set(k, v)
        exe = Executor(amp=executor.amp,
                       compute_dtype=executor.compute_dtype,
                       conv1x1_pallas=executor.conv1x1_pallas)
        compiled = exe.compile(program, feed=feed_arrays,
                               fetch_list=[], scope=sc,
                               is_test=is_test)
        from . import attribution
        return attribution.executable_facts(compiled)
    except Exception as e:
        logger.warning("opprof: compiled-side memory cross-check "
                       "unavailable (%s: %s)", type(e).__name__, e)
        return None


# ---------------------------------------------------------------------------
# Rendering
# ---------------------------------------------------------------------------
def render_profile(report: dict, top: int = 10) -> str:
    """Human-readable profile rendering (the ``profile`` CLI's text
    form)."""
    lines = [
        f"per-op profile: {report['ops']} op(s), eager total "
        f"{report['eager_total_ms']:.3f} ms, per-op sum "
        f"{report['per_op_sum_ms']:.3f} ms (gap "
        f"{round(report['sum_gap_frac'] * 100, 2)}%"
        + ("" if report["within_tolerance"] else " — OVER TOLERANCE")
        + ")"]
    lines.append("top ops by measured time:")
    for r in report["top"][:top]:
        m = r.get("modeled") or {}
        share = (r["wall_ms"] / report["per_op_sum_ms"] * 100
                 if report["per_op_sum_ms"] else 0.0)
        extra = ""
        if m:
            extra = (f"  pred {m['predicted_ms']:.6f} ms"
                     + (f"  ratio {r['ratio']}x"
                        if r.get("ratio") is not None else "")
                     + f"  {m['roofline']}")
        lines.append(f"  #{r['index']:>3} {r['op_type']:<22} "
                     f"{r['wall_ms']:10.3f} ms ({share:4.1f}%)"
                     f" [{r['phase']}]{extra}")
    xl = report.get("xla_loses_here") or []
    if xl:
        lines.append("XLA loses here (by op class):")
        for e in xl[:top]:
            line = (f"  {e['op_type']} (x{e['count']}): "
                    f"{e['measured_ms']:.3f} ms "
                    f"({round(e['share'] * 100, 1)}%)"
                    + (f", ratio {e['ratio']}x" if e.get("ratio") else ""))
            if e.get("pallas_candidate"):
                line += (f" -> {e['pallas_candidate']}"
                         + (" [pending hardware]"
                            if e.get("pending_hardware") else ""))
            lines.append(line)
            if e.get("decision_rule"):
                lines.append(f"      rule: {e['decision_rule']}")
    mem = report.get("memory") or {}
    if mem:
        line = (f"memory: measured peak "
                f"{mem['peak_bytes'] / 1e6:.3f} MB at op "
                f"#{mem['peak_index']} ({mem['peak_op']})")
        if mem.get("modeled_peak_bytes"):
            line += (f"; modeled {mem['modeled_peak_bytes'] / 1e6:.3f} MB"
                     + (f" (ratio {mem['peak_ratio']})"
                        if mem.get("peak_ratio") else ""))
        lines.append(line)
        ex = mem.get("executable")
        if ex and isinstance(ex, dict) and ex.get("memory"):
            lines.append(f"  compiled-side memory_analysis: {ex['memory']}")
    return "\n".join(lines)
