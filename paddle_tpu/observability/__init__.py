"""Unified runtime observability: metrics registry, step/pipeline
telemetry sinks, XProf annotation labels, NaN provenance, and structured
export.

The v1 reference shipped with pervasive built-in telemetry — ``StatSet``
per-layer timers printed every ``log_period`` (utils/Stat.h, Flags.cpp:62)
— and this package is its TPU-native successor, one layer for every
execution path:

* :mod:`.metrics` — thread-safe typed registry (counters / gauges /
  histograms with fixed buckets) behind the frozen ``METRIC_NAMES`` table;
* :mod:`.export` — JSONL structured event log (``PADDLE_TPU_METRICS_LOG``),
  ``metrics_snapshot()``, device-memory sampling, ``log_period`` periodic
  reports, multi-file log merging, Prometheus text exposition, and the
  ``python -m paddle_tpu stats`` summarizer;
* :mod:`.tracing` — structured spans (frozen ``SPAN_NAMES``) across the
  reader → staging → dispatch → fetch and serving request chains, with
  the ``python -m paddle_tpu trace`` timeline/critical-path engine;
* :mod:`.attribution` — the measured-vs-modeled ``doctor``: step/request
  budgets, compiled-executable facts, cost-model calibration (imported
  LAZILY — it pulls analysis.cost_model; repo-lint enforced);
* :mod:`.nanprov` — eager per-op bisect of a ``check_nan_inf`` failure.

Producers: ``Executor.run/run_steps/run_pipelined`` (per-step wall time,
dispatch size, feed bytes, staging/fetch-block time — gated by the
``observe`` flag / ``Executor(observe=...)``), ``reader.pipeline`` (queue
depth, worker busy/wait, consumer stalls), the trainer (periodic
reports), and ``core.compile_cache`` (re-exported through
``metrics_snapshot()['compile']``).  ``paddle_tpu.profiler.report()``
renders the merged StatSet + CompileStats + Metrics view.

**Zero overhead when off** is a hard contract: with ``observe`` false the
hot paths never reach a registry write and never change a traced
computation (tier-1 asserts both — no counter deltas, no retraces).
"""
from .metrics import (METRIC_NAMES, MetricsRegistry, enabled, inc_counter,
                      observe_hist, registry, set_gauge)
from .export import (emit_event, iter_log_events, log_path,
                     maybe_periodic_report, metrics_snapshot,
                     periodic_report, process_identity,
                     sample_device_memory, set_process_identity,
                     source_label, summarize_log, summarize_logs,
                     to_prometheus)
from . import tracing
from .tracing import SPAN_NAMES

__all__ = [
    "METRIC_NAMES", "MetricsRegistry", "registry", "enabled",
    "inc_counter", "set_gauge", "observe_hist",
    "emit_event", "log_path", "metrics_snapshot", "sample_device_memory",
    "periodic_report", "maybe_periodic_report", "summarize_log",
    "summarize_logs", "iter_log_events", "to_prometheus",
    "set_process_identity", "process_identity", "source_label",
    "tracing", "SPAN_NAMES",
    "report",
]


def report() -> str:
    """StatSet-style text block of the metrics registry."""
    return registry().report()
