"""Fleet-wide metrics aggregation: merge per-process
``metrics_snapshot()`` dicts into ONE labeled fleet snapshot.

A distributed job runs as many processes — trainers, pserver shards,
serve replicas, a fleet router, a master — each with its own in-process
metrics registry.  This module is the read side: it gathers one
snapshot per process over whichever channel that process already
exposes, then merges them:

* **JSONL logs** (:func:`collect_logs`) — the last ``snapshot`` event of
  each per-process metrics log, labeled by the file's identity header
  (``pserver:1``, ``serve:0``, ...);
* **pserver endpoints** (:func:`collect_endpoints`) — one ``stats`` op
  per shard with the opt-in ``metrics`` field (the default stats reply
  stays byte-stable; sparse/pserver.py);
* **a master** (:func:`collect_master`) — the opt-in ``metrics``
  heartbeat piggyback (distributed/master.py);
* **a live fleet router** (:func:`collect_router`) —
  ``FleetRouter.metrics_snapshots()``, which piggybacks on the replica
  health poll (serving/fleet.py).

Merge semantics (:func:`merge_snapshots`): counters SUM across sources
(fleet totals), gauges keep one sample per source (the label is
prefixed ``<source>:`` — a gauge is a per-process level, summing it
lies), histograms merge bucket-wise when boundaries match (they do
within one release; a skewed source is skipped and named), compile
counters sum, device memory keys get the source prefix.

``python -m paddle_tpu fleet-stats <dir-or-logs-or-endpoints>``
(:func:`fleet_stats_main`) is the CLI form; ``--prom`` renders the
merged snapshot in Prometheus text exposition.

Imported LAZILY by design (repo-lint enforced, like ``attribution``):
collecting can dial sockets and pull the sparse wire stack — importing
``paddle_tpu.observability`` must stay cheap and socket-free.
"""
from __future__ import annotations

import argparse
import json
import logging
import os
import socket
from typing import Dict, List, Optional, Sequence

from . import metrics as _metrics
from .export import iter_log_events, to_prometheus

logger = logging.getLogger("paddle_tpu")

__all__ = [
    "merge_snapshots", "collect_logs", "collect_endpoints",
    "collect_master", "collect_router", "render_fleet",
    "fleet_stats_main",
]


def _source_name(identity: Optional[dict], fallback: str) -> str:
    """``pserver:1`` / ``serve`` from a piggybacked identity dict, else
    the fallback (file basename, endpoint address)."""
    if isinstance(identity, dict) and identity.get("role"):
        idx = identity.get("index")
        return (f"{identity['role']}:{idx}" if idx is not None
                else str(identity["role"]))
    return str(fallback)


def _unique(existing, name: str) -> str:
    """Two trainers both named ``main`` must not silently overwrite each
    other in the sources dict."""
    if name not in existing:
        return name
    i = 2
    while f"{name}#{i}" in existing:
        i += 1
    return f"{name}#{i}"


# ---------------------------------------------------------------------------
# The merge
# ---------------------------------------------------------------------------
def merge_snapshots(sources: Dict[str, dict]) -> dict:
    """Merge per-process snapshots into one fleet view.

    ``sources``: ``{source_name: {"metrics": <metrics_snapshot() dict>,
    "identity": {...}|None}}`` — the shape every ``collect_*`` frontend
    returns (``"metrics"`` may also be a bare registry snapshot).

    Returns ``{"sources", "metrics", "compile", "device_memory"
    [, "skipped"]}`` where ``metrics`` is registry-snapshot shaped, so
    :func:`..export.to_prometheus` renders it unchanged.
    """
    merged: Dict[str, dict] = {}
    compile_: Dict[str, float] = {}
    device_memory: Dict[str, dict] = {}
    identities: Dict[str, Optional[dict]] = {}
    skipped: List[str] = []
    for src in sorted(sources):
        entry = sources[src] or {}
        identities[src] = entry.get("identity")
        snap = entry.get("metrics")
        if not isinstance(snap, dict):
            skipped.append(f"{src} (no snapshot)")
            continue
        registry = snap.get("metrics", snap)
        if not isinstance(registry, dict):
            registry = {}
        for name, m in registry.items():
            if not isinstance(m, dict):
                continue
            kind = m.get("kind")
            have = merged.get(name)
            if kind == "counter":
                if have is None:
                    have = merged[name] = {"kind": "counter", "value": 0.0}
                have["value"] += float(m.get("value") or 0.0)
            elif kind == "gauge":
                if have is None:
                    have = merged[name] = {"kind": "gauge", "values": {}}
                for label, v in (m.get("values") or {}).items():
                    key = f"{src}:{label}" if label else src
                    have["values"][key] = v
            elif kind == "histogram":
                bounds = list(m.get("boundaries") or ())
                if have is None:
                    have = merged[name] = {
                        "kind": "histogram", "count": 0, "sum": 0.0,
                        "min": None, "max": None, "boundaries": bounds,
                        "counts": [0] * len(bounds)}
                if bounds != have["boundaries"]:
                    # bucket skew (a mixed-release fleet): adding counts
                    # across different edges fabricates a distribution —
                    # name the source instead of lying
                    skipped.append(f"{src}:{name} (bucket mismatch)")
                    continue
                have["count"] += int(m.get("count") or 0)
                have["sum"] = round(have["sum"]
                                    + float(m.get("sum") or 0.0), 6)
                have["counts"] = [a + b for a, b in
                                  zip(have["counts"],
                                      m.get("counts") or [0] * len(bounds))]
                for agg, pick in (("min", min), ("max", max)):
                    v = m.get(agg)
                    if v is not None:
                        have[agg] = v if have[agg] is None \
                            else pick(have[agg], v)
        for k, v in (snap.get("compile") or {}).items():
            if isinstance(v, (int, float)):
                compile_[k] = compile_.get(k, 0.0) + float(v)
        for dev, stats in (snap.get("device_memory") or {}).items():
            device_memory[f"{src}:{dev}"] = stats
    _metrics.inc_counter("collector/merges")
    _metrics.set_gauge("collector/sources", len(sources))
    out = {"sources": identities, "metrics": merged,
           "compile": compile_, "device_memory": device_memory}
    if skipped:
        out["skipped"] = skipped
    return out


# ---------------------------------------------------------------------------
# Collection frontends (one per channel a process already exposes)
# ---------------------------------------------------------------------------
def collect_logs(paths: Sequence) -> Dict[str, dict]:
    """Last ``snapshot`` event of each JSONL metrics log, labeled by the
    file's identity header.  Files without a snapshot are skipped with a
    warning (a log from an observe-off run has none)."""
    sources: Dict[str, dict] = {}
    for path in paths:
        try:
            events, files = iter_log_events(path)
        except OSError as e:
            logger.warning("fleet collector: cannot read %r: %s", path, e)
            continue
        snap = next((e for e in reversed(events)
                     if e.get("kind") == "snapshot"), None)
        if snap is None:
            logger.warning("fleet collector: %r has no snapshot events "
                           "(observe off, or no periodic_report)", path)
            continue
        f = files[0]
        identity = None
        if f.get("role"):
            identity = {"role": f["role"], "pid": f.get("pid")}
            if f.get("proc_index") is not None:
                identity["index"] = f["proc_index"]
        name = _unique(sources, _source_name(
            identity, os.path.basename(str(path))))
        sources[name] = {
            "metrics": {k: snap.get(k)
                        for k in ("metrics", "compile", "device_memory")},
            "identity": identity}
    return sources


def collect_endpoints(addrs: Sequence[str],
                      timeout_s: float = 5.0) -> Dict[str, dict]:
    """Poll live pserver shards: one short-lived connection per
    ``host:port``, a ``stats`` op with the opt-in ``metrics`` field.
    Unreachable shards are skipped with a warning — a fleet snapshot
    that names what answered beats an exception that names nothing."""
    from ..sparse import wire  # lazy: the socket wire stack

    sources: Dict[str, dict] = {}
    for a in addrs:
        host, _, port = str(a).rpartition(":")
        try:
            with socket.create_connection((host, int(port)),
                                          timeout=timeout_s) as s:
                s.settimeout(timeout_s)
                wire.write_frame(s, {"op": "hello"})
                hello, _ = wire.read_frame(s)
                wire.write_frame(s, {"op": "stats", "metrics": True})
                reply, _ = wire.read_frame(s)
        except (OSError, ValueError, wire.WireError) as e:
            logger.warning("fleet collector: pserver %s unreachable: %s",
                           a, e)
            continue
        if not reply.get("ok") or not isinstance(reply.get("metrics"),
                                                 dict):
            logger.warning("fleet collector: pserver %s did not piggyback "
                           "metrics (reply keys: %s)", a,
                           sorted(reply))
            continue
        identity = reply.get("identity")
        if not isinstance(identity, dict):
            identity = {"role": "pserver", "index": hello.get("shard")}
        name = _unique(sources, _source_name(identity, str(a)))
        sources[name] = {"metrics": reply["metrics"],
                         "identity": identity}
    return sources


def collect_master(target, slot: int = -1) -> Dict[str, dict]:
    """One ``metrics=True`` heartbeat against a master — ``target`` is a
    ``MasterClient`` or a ``host:port`` string.  The poll heartbeats as
    ``slot`` (default -1, a slot no worker uses, so the collector's
    lease refresh never masks a real worker's staleness)."""
    if isinstance(target, str):
        from ..distributed.master import MasterClient  # lazy: socket stub
        target = MasterClient(target)
    reply = target.heartbeat(slot, metrics=True)
    if not isinstance(reply.get("metrics"), dict):
        logger.warning("fleet collector: master did not piggyback "
                       "metrics (reply keys: %s)", sorted(reply))
        return {}
    identity = reply.get("identity")
    return {_source_name(identity, "master"):
            {"metrics": reply["metrics"], "identity": identity}}


def collect_router(router, timeout_s: float = 2.0) -> Dict[str, dict]:
    """Snapshot a live in-process ``FleetRouter``'s replicas (the
    health-poll piggyback; serving/fleet.py) into source form."""
    out: Dict[str, dict] = {}
    for rep_name, entry in router.metrics_snapshots(
            timeout_s=timeout_s).items():
        identity = entry.get("identity")
        name = _unique(out, _source_name(identity, rep_name))
        out[name] = {"metrics": entry.get("metrics"),
                     "identity": identity}
    return out


# ---------------------------------------------------------------------------
# Rendering + CLI
# ---------------------------------------------------------------------------
def render_fleet(merged: dict) -> str:
    """Human-readable rendering of :func:`merge_snapshots` output."""
    idents = merged.get("sources") or {}
    lines = [f"fleet snapshot: {len(idents)} source(s)"]
    for src in sorted(idents):
        ident = idents[src]
        pid = ident.get("pid") if isinstance(ident, dict) else None
        lines.append(f"  source {src}"
                     + (f" (pid {pid})" if pid is not None else ""))
    for name, m in sorted((merged.get("metrics") or {}).items()):
        kind = m.get("kind")
        if kind == "counter" and m.get("value"):
            lines.append(f"  {name}: {m['value']:g}")
        elif kind == "gauge" and m.get("values"):
            vals = " ".join(f"{k}={v:g}" for k, v in
                            sorted(m["values"].items()))
            lines.append(f"  {name}: {vals}")
        elif kind == "histogram" and m.get("count"):
            mean = m["sum"] / m["count"]
            lines.append(
                f"  {name}: count={m['count']} mean={mean:.3f} "
                f"p50={_metrics.histogram_quantile(m, 0.5):.3f} "
                f"p90={_metrics.histogram_quantile(m, 0.9):.3f} "
                f"max={m['max']}")
    comp = merged.get("compile") or {}
    if any(comp.values()):
        lines.append("  compile: " + " ".join(
            f"{k.partition('/')[2]}={v:g}" for k, v in sorted(comp.items())
            if v))
    for s in merged.get("skipped") or ():
        lines.append(f"  skipped: {s}")
    return "\n".join(lines)


def fleet_stats_main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="paddle_tpu fleet-stats",
        description="merge per-process metrics snapshots into one "
                    "labeled fleet snapshot (paddle_tpu.observability."
                    "collector): sources are JSONL metrics logs (files "
                    "or a directory of them — each file's LAST snapshot "
                    "event, labeled by its identity header) and/or live "
                    "pserver shard endpoints (host:port — a stats op "
                    "with the opt-in metrics piggyback).  Counters sum "
                    "across sources; gauges stay per-source; histograms "
                    "merge bucket-wise.  --prom renders Prometheus text "
                    "exposition for scraping.")
    ap.add_argument("source", nargs="+",
                    help="JSONL log file, a directory of *.jsonl logs, "
                         "or a pserver host:port endpoint (mixable)")
    ap.add_argument("--master", default=None, metavar="HOST:PORT",
                    help="also poll a distributed master's heartbeat "
                         "metrics piggyback")
    ap.add_argument("--slot", type=int, default=-1,
                    help="slot the master poll heartbeats as (default "
                         "-1: no real worker's lease is touched)")
    ap.add_argument("--timeout-s", type=float, default=5.0,
                    help="per-endpoint dial/reply timeout (default 5)")
    ap.add_argument("--json", action="store_true",
                    help="print the merged snapshot as ONE JSON object "
                         "only")
    ap.add_argument("--prom", action="store_true",
                    help="print the merged snapshot in Prometheus text "
                         "exposition format and exit")
    args = ap.parse_args(argv)

    logs: List[str] = []
    endpoints: List[str] = []
    for src in args.source:
        if os.path.isdir(src):
            found = sorted(
                os.path.join(src, f) for f in os.listdir(src)
                if f.endswith(".jsonl"))
            if not found:
                raise SystemExit(f"fleet-stats: no *.jsonl logs in "
                                 f"directory {src!r}")
            logs.extend(found)
        elif os.path.exists(src):
            logs.append(src)
        else:
            host, sep, port = src.rpartition(":")
            if sep and host and port.isdigit():
                endpoints.append(src)
            else:
                raise SystemExit(
                    f"fleet-stats: {src!r} is neither an existing "
                    f"log/directory nor a host:port endpoint")

    sources: Dict[str, dict] = {}
    for name, entry in collect_logs(logs).items():
        sources[_unique(sources, name)] = entry
    for name, entry in collect_endpoints(
            endpoints, timeout_s=args.timeout_s).items():
        sources[_unique(sources, name)] = entry
    if args.master:
        try:
            polled = collect_master(args.master, slot=args.slot)
        except (OSError, ConnectionError) as e:
            logger.warning("fleet collector: master %s unreachable: %s",
                           args.master, e)
            polled = {}
        for name, entry in polled.items():
            sources[_unique(sources, name)] = entry
    if not sources:
        raise SystemExit(
            "fleet-stats: no snapshots collected — logs need snapshot "
            "events (observe on + periodic_report/log_period) and "
            "endpoints must be reachable pserver shards")
    merged = merge_snapshots(sources)
    if args.prom:
        print(to_prometheus({"metrics": merged["metrics"],
                             "compile": merged["compile"]}),
              end="", flush=True)
        return 0
    if not args.json:
        print(render_fleet(merged), flush=True)
    print(json.dumps(merged, default=repr), flush=True)
    return 0
