"""Typed, thread-safe metrics registry — the quantitative half of the
observability layer (reference analog: v1's ``StatSet`` of named ``Stat``
timers, utils/Stat.h:63,114,230, printed every ``log_period``).

Three metric kinds, all namespaced ``<subsystem>/<name>``:

* **counter** — monotonically increasing float (steps, bytes, seconds).
* **gauge** — last-written value, optionally per label (examples/sec,
  per-device memory).
* **histogram** — fixed bucket boundaries chosen per metric at registry
  definition time, plus count/sum/min/max (step times, queue depths).

Every metric name is a LITERAL member of the frozen :data:`METRIC_NAMES`
table below; the module-level helpers (:func:`inc_counter`,
:func:`set_gauge`, :func:`observe_hist`) reject unknown names at runtime
and ``tests/test_repo_lint.py`` rejects non-literal or unregistered names
at lint time — a typo'd metric name is a test failure, not a silently
empty time series.

Writers are gated by their CALL SITES (``Executor._observing()``,
``reader.pipeline``'s ``instrument`` resolution), not here: with the
``observe`` flag off the hot paths never reach these helpers, which is
what the zero-overhead-when-off tier-1 assertion pins.
"""
from __future__ import annotations

import bisect as _bisect
import threading
from typing import Dict, List, Optional, Tuple

__all__ = [
    "METRIC_NAMES", "THREAD_NAME_PREFIXES", "HISTOGRAM_BUCKETS",
    "MetricsRegistry", "registry",
    "inc_counter", "set_gauge", "observe_hist", "enabled",
]

# ---------------------------------------------------------------------------
# Frozen metric-name registry.  (name, kind, help) — names used through the
# helpers below MUST appear here as literals (AST-gated in
# tests/test_repo_lint.py; duplicates rejected at import AND lint time).
# ---------------------------------------------------------------------------
METRIC_NAMES = (
    ("executor/steps", "counter",
     "training/inference steps executed (a K-step scan counts K)"),
    ("executor/dispatches", "counter",
     "compiled dispatches issued (run=1 step, run_steps=K steps)"),
    ("executor/step_time_ms", "histogram",
     "per-step wall time: dispatch wall / steps in the dispatch"),
    ("executor/dispatch_steps", "histogram",
     "steps per compiled dispatch (K of run_steps / run_pipelined chunks)"),
    ("executor/feed_bytes", "counter",
     "feed bytes entering dispatches (after dtype coercion)"),
    ("executor/fetch_block_ms", "histogram",
     "host time blocked materializing fetches to numpy"),
    ("executor/stage_put_ms", "histogram",
     "device_put staging time per run_pipelined chunk (staging thread)"),
    ("executor/examples_per_sec", "gauge",
     "examples/sec of the most recent dispatch (batch * K / wall)"),
    ("executor/nan_events", "counter",
     "check_nan_inf trips that ran the NaN-provenance bisect"),
    ("pipeline/queue_depth", "histogram",
     "prefetch queue depth sampled at each consumer get"),
    ("pipeline/consumer_stall_ms", "histogram",
     "consumer time blocked on an empty prefetch queue"),
    ("pipeline/worker_busy_s", "counter",
     "pipeline-worker seconds spent producing (decode/stage work)"),
    ("pipeline/worker_wait_s", "counter",
     "pipeline-worker seconds blocked on a full queue (backpressure)"),
    ("trainer/reports", "counter",
     "periodic log_period reports emitted by the trainer"),
    ("device/bytes_in_use", "gauge",
     "live device memory per device (memory_stats, where supported)"),
    ("device/peak_bytes_in_use", "gauge",
     "peak device memory per device (memory_stats, where supported)"),
    # fault-tolerance events (cold paths: written unconditionally — the
    # zero-overhead-when-off contract covers per-step hot paths, and a
    # run's fault history must survive into `stats` regardless of observe)
    ("fault/injected", "counter",
     "deterministic fault injections fired (testing.faultinject)"),
    ("fault/retries", "counter",
     "transient-error retries at the dispatch and master RPC rims"),
    ("fault/preemptions", "counter",
     "SIGTERM/SIGINT preemptions that took an emergency checkpoint"),
    ("fault/restarts", "counter",
     "supervisor relaunches of a preempted/transiently-failed run"),
    ("fault/checkpoint_saves", "counter",
     "trainer checkpoint commits (periodic + emergency)"),
    ("fault/checkpoint_restores", "counter",
     "successful checkpoint restores into a training run"),
    ("fault/checkpoint_fallbacks", "counter",
     "restores that skipped a corrupt/truncated checkpoint for an older "
     "intact one"),
    ("fault/tasks_returned", "counter",
     "in-flight master tasks handed back before a retry/shutdown"),
    # serving runtime (paddle_tpu.serving): per-request/per-batch writes
    # are unconditional — the server IS the instrumented subsystem, and
    # its metrics are how operators see shedding/deadline behavior; the
    # zero-overhead-when-off contract covers TRAINING paths, which never
    # reach these helpers
    ("serving/requests", "counter",
     "requests admitted past admission control (a queued request may "
     "still be shed later under overload)"),
    ("serving/batches", "counter",
     "coalesced batches dispatched by the serving runtime"),
    ("serving/shed", "counter",
     "requests rejected by load shedding (Overloaded: queue full, "
     "oldest-deadline-first eviction)"),
    ("serving/deadline_expired", "counter",
     "requests whose deadline expired before dispatch (never computed)"),
    ("serving/breaker_open", "counter",
     "per-model circuit-breaker open transitions (repeated fatal errors)"),
    ("serving/queue_depth", "histogram",
     "admission queue depth sampled as each batch is formed"),
    ("serving/batch_size", "histogram",
     "live (unpadded) requests per dispatched serving batch"),
    ("serving/request_ms", "histogram",
     "admitted-request latency: admission to completed response"),
    # incremental decode serving (paddle_tpu.serving.decode): the slot
    # pool is the instrumented subsystem, same rationale as serving/*
    ("serving/decode_tokens", "counter",
     "tokens generated by decode slot pools (prefill first-tokens + one "
     "per live slot per decode step)"),
    ("serving/decode_tokens_per_s", "gauge",
     "decode throughput: cumulative generated tokens over pool uptime"),
    ("serving/decode_ttft_ms", "histogram",
     "time to first token: request admission to prefill emitting the "
     "first generated token"),
    ("serving/decode_inter_token_ms", "histogram",
     "gap between consecutive generated tokens of one sequence (the "
     "streaming cadence; its p99 is what continuous batching bounds)"),
    ("serving/decode_slot_occupancy", "gauge",
     "live sequences over total slots at the last decode step (padded "
     "compute fraction is 1 minus this)"),
    ("pipeline/fallback_steps", "counter",
     "run_pipelined steps dispatched through the per-step fallback "
     "(stream tail or padding-bucket signature change) instead of a "
     "K-step scan"),
    # autotuner (paddle_tpu.tuning): search-time writes are cold paths
    # (a search IS the workload) and replay writes fire once per
    # (call site, process) — the zero-overhead-when-off contract covers
    # untuned training paths, which never reach these helpers
    ("tuning/trials", "counter",
     "autotuner trials executed (ok + failed + timeout)"),
    ("tuning/trial_ms", "histogram",
     "wall time per autotuner trial (all windows incl. warmup)"),
    ("tuning/failures", "counter",
     "autotuner trials recorded failed or timeout (contained, never "
     "crash the search)"),
    ("tuning/winners", "counter",
     "tunables whose candidate cleared the paired-A/B noise gate and "
     "was persisted"),
    ("tuning/refusals", "counter",
     "searches ending in an explicit refusal (noise gate, or no viable "
     "config)"),
    ("tuning/replays", "counter",
     "persisted winners replayed into call sites by tuned() (first "
     "lookup per site per process)"),
    # HTTP serving front (paddle_tpu.serving.http): per-request writes
    # are unconditional, same rationale as serving/* — the front IS the
    # instrumented subsystem; training paths never reach these helpers
    ("http/requests", "counter",
     "HTTP inference requests received by the serving front"),
    ("http/rejected", "counter",
     "HTTP requests answered with a typed-rejection status (429/503/504) "
     "or a 4xx protocol error"),
    ("http/auth_failures", "counter",
     "HTTP requests rejected 401/403 by the token -> model gate"),
    ("http/request_ms", "histogram",
     "HTTP request wall time: socket read to last response byte"),
    # serving fleet (paddle_tpu.serving.fleet): router + autoscaler
    # writes are unconditional for the same reason
    ("fleet/requests", "counter",
     "requests routed to a replica by the fleet router"),
    ("fleet/failovers", "counter",
     "admitted requests resubmitted to another replica after their "
     "replica died or closed mid-flight (the zero-drop path)"),
    ("fleet/evictions", "counter",
     "replicas removed from the routable set (breaker open, draining, "
     "dead, or unresponsive health)"),
    ("fleet/relaunches", "counter",
     "dead replicas relaunched through the supervisor's bounded-restart "
     "accounting"),
    ("fleet/router_shed", "counter",
     "requests rejected Overloaded at the FLEET rim (every ready "
     "replica at the backlog limit) — cheaper than a replica-side shed "
     "that pays wire+parse first"),
    ("fleet/scale_outs", "counter",
     "autoscaler scale-out decisions executed (replica added)"),
    ("fleet/scale_ins", "counter",
     "autoscaler scale-in decisions executed (replica drained + removed)"),
    ("fleet/replicas", "gauge",
     "current fleet size by state (labels: ready/warming/draining/dead)"),
    # elastic training service (paddle_tpu.distributed.elastic): writes
    # are unconditional cold paths like fault/* — membership churn and
    # resize boundaries are rare events whose history must survive into
    # `stats`; training hot paths never reach these helpers
    ("elastic/workers", "gauge",
     "elastic worker count by state (labels: ready = live process, "
     "done = exited 0 with its shard complete)"),
    ("elastic/heartbeats", "counter",
     "worker heartbeats received through the master's membership layer"),
    ("elastic/drains", "counter",
     "coordinator-commanded worker drains completed at a task boundary"),
    ("elastic/resizes", "counter",
     "committed mesh resize boundaries (drain -> merge -> re-plan -> "
     "relaunch)"),
    ("elastic/resize_ms", "histogram",
     "wall time of one resize boundary: drain start to workers relaunched"),
    # per-op profiler (observability.opprof): writes are cold paths by
    # construction — a profile run IS the workload, like tuning; training
    # paths never reach these helpers (opprof is lazy-import gated)
    ("opprof/runs", "counter",
     "per-op profile runs executed (profile CLI / doctor --per-op)"),
    ("opprof/ops", "counter",
     "ops measured by the per-op profiler (one per op per run)"),
    ("opprof/op_ms", "histogram",
     "measured per-op eager wall time (median of timed windows)"),
    # sparse parameter server (paddle_tpu.sparse): the session resolves
    # an observe switch ONCE at construction (obs.enabled() unless
    # overridden) and only writes when observing — training paths that
    # never build a session never reach these helpers (the package is
    # lazy-import gated like serving/tuning/elastic)
    ("sparse/pulls", "counter",
     "sparse-table pulls executed (one per bound table per batch)"),
    ("sparse/pulled_rows", "counter",
     "unique live rows pulled from host sparse tables"),
    ("sparse/pushes", "counter",
     "sparse-table gradient pushes applied (one per table per batch)"),
    ("sparse/pushed_rows", "counter",
     "rows updated by host-side sparse optimizer pushes"),
    ("sparse/pull_ms", "histogram",
     "host wall time of one table pull (dedup'd batch rows, cache-first)"),
    ("sparse/push_ms", "histogram",
     "host wall time of one gradient push (sparse optimizer update)"),
    ("sparse/cache_hits", "counter",
     "hot-rows cache hits on the pull path"),
    ("sparse/cache_misses", "counter",
     "hot-rows cache misses on the pull path (row fetched from shard)"),
    ("sparse/live_rows", "gauge",
     "lazily-materialized rows resident per table (labels: table name)"),
    ("sparse/rows_initialized", "counter",
     "rows lazily initialized by the batched Philox draw (cold-row "
     "materializations inside pulls/pushes)"),
    ("sparse/init_rows_per_sec", "gauge",
     "lazy-init throughput of the most recent cold-row batch (labels: "
     "table name) — the vectorized-vs-scalar init signal"),
    ("sparse/prefetch_hits", "counter",
     "pull-ahead prefetch hits: the consumer found the next batch "
     "already prepared (overlap won)"),
    ("sparse/prefetch_misses", "counter",
     "pull-ahead prefetch misses: the consumer blocked on the worker "
     "(pulls slower than dispatch, or depth too small)"),
    ("sparse/push_flush_ms", "histogram",
     "host wall time of one async-push worker drain (up to "
     "push_flush_batch queued gradient pushes applied FIFO)"),
    # sparse parameter-server wire tier (sparse.pserver / sparse.client):
    # only written inside pserver processes and RemoteSparseTable rounds —
    # the tier is lazy-import gated, so in-process training never loads it
    ("pserver/requests", "counter",
     "wire requests served by pserver shards (one per batched frame)"),
    ("pserver/pull_rows", "counter",
     "rows pulled through the pserver wire path (server-side count)"),
    ("pserver/push_rows", "counter",
     "rows updated by pserver-side optimizer pushes"),
    ("pserver/pull_rows_per_sec", "gauge",
     "server-side kernel throughput of the most recent batched pull"),
    ("pserver/push_rows_per_sec", "gauge",
     "server-side kernel throughput of the most recent batched push"),
    ("pserver/wire_bytes_in", "counter",
     "bytes received over the pserver binary wire (frames in)"),
    ("pserver/wire_bytes_out", "counter",
     "bytes sent over the pserver binary wire (frames out)"),
    ("pserver/frame_ms", "histogram",
     "server wall time of one batched request frame: decode done to "
     "reply queued (the wire-marshalling + kernel cost per round)"),
    ("pserver/reconnects", "counter",
     "client reconnects to a pserver shard (retry rim re-dials after a "
     "torn frame / refused connection)"),
    ("pserver/replication_lag_ms", "histogram",
     "chain-backup forward round-trip per applied push: apply done to "
     "backup ack (the price of zero-acked-push-loss durability)"),
    ("pserver/backup_pushes", "counter",
     "chain-backup pushes applied on behalf of a predecessor shard"),
    ("pserver/checkpoints", "counter",
     "durable pserver shard checkpoints committed (SIGTERM or op)"),
    # incremental checkpointing (distributed.checkpoint delta chains)
    ("checkpoint/delta_bytes", "counter",
     "bytes written by delta commits (sparse dirty rows + dense chunk "
     "patches) — the wire/disk cost full rebases amortize away"),
    ("checkpoint/delta_rows", "counter",
     "sparse rows serialized into delta commits (dirty rows only)"),
    ("checkpoint/rebase_total", "counter",
     "full commits that terminated a live delta chain (policy rebase "
     "or forced fallback after a chain error)"),
    ("checkpoint/commit_ms", "histogram",
     "writer wall time of one durable commit, serialize to fsync'd "
     "meta (full and delta alike; the trainer only pays this when a "
     "hard barrier drains the queue)"),
    # distributed tracing wire rim (observability.tracing inject/extract):
    # context_rejected is an anomaly counter like fault/* — a malformed
    # context only exists when a peer SENT one, so counting it is never
    # on a zero-overhead-off path
    ("trace/context_rejected", "counter",
     "malformed/truncated/unknown-version trace contexts rejected at a "
     "wire rim (ignored-and-counted: the request still serves)"),
    # fleet metrics collector (observability.collector, lazy-import
    # gated like attribution/opprof): only written inside collector
    # merges — a fleet-stats run IS the workload
    ("collector/merges", "counter",
     "fleet snapshot merges executed by the metrics collector"),
    ("collector/sources", "gauge",
     "per-process sources folded into the most recent fleet snapshot "
     "(labels: source kind — log/pserver/master/replica)"),
    # lock-order watchdog (testing.lockwatch): writes only happen when
    # PADDLE_TPU_LOCKWATCH is on — the factories return PLAIN threading
    # primitives when off, so production paths never reach these helpers
    ("concurrency/order_violations", "counter",
     "lock-acquisition-order cycles detected by lockwatch (each raised "
     "as a deterministic LockOrderViolation instead of deadlocking)"),
    ("concurrency/order_edges", "gauge",
     "distinct lock-class ordering edges in the process-wide lockwatch "
     "acquisition graph"),
    ("concurrency/long_holds", "counter",
     "lock holds exceeding the PADDLE_TPU_LOCKWATCH_HOLD_MS watchdog "
     "threshold"),
    ("concurrency/lock_held_ms", "histogram",
     "watched-lock hold time, acquire to release (lockwatch on only)"),
)

# ---------------------------------------------------------------------------
# Frozen framework thread-name prefixes.  (prefix, help) — every thread the
# framework starts MUST carry a name beginning with one of these (AST-gated
# by the PT055 concurrency pass + tests/test_repo_lint.py; runtime-asserted
# by the conftest thread-leak fixture), so leak reports, `stats` output and
# operator tooling can attribute any thread to its subsystem by name alone.
# ---------------------------------------------------------------------------
THREAD_NAME_PREFIXES = (
    ("pt-input-pipeline", "reader pipeline prefetch workers"),
    ("pt-reader", "reader decorator xmap/pipe workers"),
    ("pt-sparse", "sparse session prefetch + async-push workers"),
    ("pt-ckpt", "incremental checkpoint commit writer"),
    ("pt-serving", "serving batcher/dispatcher/stdin threads"),
    ("pt-decode", "continuous-batching decode loop"),
    ("pt-http", "HTTP serving front acceptor"),
    ("pt-fleet", "fleet router/drain/autoscale/replica-io threads"),
    ("pt-elastic", "elastic worker heartbeat daemons"),
    ("pt-master", "distributed master RPC server"),
    ("pt-pserver", "sparse pserver selector/acceptor loops"),
    ("pt-tune", "autotuner trial client threads"),
)

_MS_BUCKETS = (0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 25.0, 50.0,
               100.0, 250.0, 500.0, 1000.0, 2500.0, 5000.0, 10000.0)
_COUNT_BUCKETS = (1.0, 2.0, 4.0, 8.0, 16.0, 32.0, 64.0, 128.0, 256.0)
_DEPTH_BUCKETS = (0.0, 1.0, 2.0, 4.0, 8.0, 16.0, 32.0, 64.0, 128.0)

# Fixed bucket boundaries per histogram (upper-inclusive edges; one
# implicit overflow bucket past the last edge).
HISTOGRAM_BUCKETS: Dict[str, Tuple[float, ...]] = {
    "executor/step_time_ms": _MS_BUCKETS,
    "executor/dispatch_steps": _COUNT_BUCKETS,
    "executor/fetch_block_ms": _MS_BUCKETS,
    "executor/stage_put_ms": _MS_BUCKETS,
    "pipeline/queue_depth": _DEPTH_BUCKETS,
    "pipeline/consumer_stall_ms": _MS_BUCKETS,
    "serving/queue_depth": _DEPTH_BUCKETS,
    "serving/batch_size": _COUNT_BUCKETS,
    "serving/request_ms": _MS_BUCKETS,
    "serving/decode_ttft_ms": _MS_BUCKETS,
    "serving/decode_inter_token_ms": _MS_BUCKETS,
    "tuning/trial_ms": _MS_BUCKETS,
    "http/request_ms": _MS_BUCKETS,
    "opprof/op_ms": _MS_BUCKETS,
    "elastic/resize_ms": _MS_BUCKETS,
    "sparse/pull_ms": _MS_BUCKETS,
    "sparse/push_ms": _MS_BUCKETS,
    "sparse/push_flush_ms": _MS_BUCKETS,
    "pserver/frame_ms": _MS_BUCKETS,
    "pserver/replication_lag_ms": _MS_BUCKETS,
    "checkpoint/commit_ms": _MS_BUCKETS,
}
_DEFAULT_BUCKETS = _MS_BUCKETS


class _Counter:
    kind = "counter"
    __slots__ = ("value",)

    def __init__(self):
        self.value = 0.0

    def snapshot(self):
        return {"kind": "counter", "value": self.value}


class _Gauge:
    kind = "gauge"
    __slots__ = ("values",)

    def __init__(self):
        self.values: Dict[str, float] = {}

    def snapshot(self):
        return {"kind": "gauge", "values": dict(self.values)}


class _Histogram:
    kind = "histogram"
    __slots__ = ("boundaries", "counts", "count", "sum", "min", "max")

    def __init__(self, boundaries: Tuple[float, ...]):
        self.boundaries = tuple(float(b) for b in boundaries)
        self.counts: List[int] = [0] * (len(self.boundaries) + 1)
        self.count = 0
        self.sum = 0.0
        self.min: Optional[float] = None
        self.max: Optional[float] = None

    def observe(self, value: float):
        self.counts[_bisect.bisect_left(self.boundaries, value)] += 1
        self.count += 1
        self.sum += value
        if self.min is None or value < self.min:
            self.min = value
        if self.max is None or value > self.max:
            self.max = value

    def snapshot(self):
        return {"kind": "histogram", "count": self.count,
                "sum": round(self.sum, 6), "min": self.min, "max": self.max,
                "boundaries": list(self.boundaries),
                "counts": list(self.counts)}


class MetricsRegistry:
    """All metrics from :data:`METRIC_NAMES`, behind ONE lock.

    Writes come from the executor dispatch path, pipeline worker threads
    and the run_pipelined staging thread concurrently; a single lock is
    cheap at the write rates involved (per dispatch / per queue op, not
    per tensor element)."""

    def __init__(self, spec=METRIC_NAMES):
        self._lock = threading.Lock()
        self._spec = spec
        self._metrics: Dict[str, object] = {}
        seen = set()
        for name, kind, _help in spec:
            if name in seen:
                raise ValueError(f"duplicate metric name {name!r} in "
                                 f"METRIC_NAMES")
            seen.add(name)
            if kind == "counter":
                self._metrics[name] = _Counter()
            elif kind == "gauge":
                self._metrics[name] = _Gauge()
            elif kind == "histogram":
                self._metrics[name] = _Histogram(
                    HISTOGRAM_BUCKETS.get(name, _DEFAULT_BUCKETS))
            else:
                raise ValueError(f"metric {name!r}: unknown kind {kind!r}")

    def _get(self, name: str, kind: str):
        m = self._metrics.get(name)
        if m is None:
            raise KeyError(
                f"unknown metric {name!r}; metric names are frozen in "
                f"observability.metrics.METRIC_NAMES — add it there (the "
                f"repo lint enforces literal, registered names)")
        if m.kind != kind:
            raise TypeError(f"metric {name!r} is a {m.kind}, not a {kind}")
        return m

    # -- writes ----------------------------------------------------------
    def inc_counter(self, name: str, n: float = 1.0):
        with self._lock:
            self._get(name, "counter").value += n

    def set_gauge(self, name: str, value: float, label: str = ""):
        with self._lock:
            self._get(name, "gauge").values[str(label)] = float(value)

    def observe_hist(self, name: str, value: float):
        with self._lock:
            self._get(name, "histogram").observe(float(value))

    # -- reads -----------------------------------------------------------
    def snapshot(self) -> Dict[str, dict]:
        """{name: snapshot-dict} for every registered metric (zero-valued
        metrics included, so consumers see a stable schema)."""
        with self._lock:
            return {name: m.snapshot() for name, m in self._metrics.items()}

    def report(self) -> str:
        """StatSet-style text block of every non-empty metric."""
        lines = ["======= Metrics ======="]
        for name, snap in sorted(self.snapshot().items()):
            if snap["kind"] == "counter" and snap["value"]:
                lines.append(f"  {name}: {snap['value']:g}")
            elif snap["kind"] == "gauge" and snap["values"]:
                vals = " ".join(f"{k or '-'}={v:g}"
                                for k, v in sorted(snap["values"].items()))
                lines.append(f"  {name}: {vals}")
            elif snap["kind"] == "histogram" and snap["count"]:
                mean = snap["sum"] / snap["count"]
                lines.append(
                    f"  {name}: count={snap['count']} mean={mean:.3f} "
                    f"min={snap['min']:.3f} max={snap['max']:.3f} "
                    f"p50={histogram_quantile(snap, 0.5):.3f} "
                    f"p90={histogram_quantile(snap, 0.9):.3f}")
        return "\n".join(lines)

    def reset(self):
        with self._lock:
            fresh = MetricsRegistry(self._spec)
            self._metrics = fresh._metrics


def histogram_quantile(snap: dict, q: float) -> float:
    """Approximate quantile from a histogram snapshot: the upper edge of
    the bucket containing the q-th observation (max for the overflow
    bucket); 0.0 for an empty histogram."""
    total = snap["count"]
    if not total:
        return 0.0
    rank = q * total
    acc = 0
    for i, c in enumerate(snap["counts"]):
        acc += c
        if acc >= rank and c:
            if i < len(snap["boundaries"]):
                return float(snap["boundaries"][i])
            return float(snap["max"])
    return float(snap["max"])


_registry = MetricsRegistry()


def registry() -> MetricsRegistry:
    return _registry


def enabled() -> bool:
    """The global ``observe`` flag (env ``PADDLE_TPU_OBSERVE``).  Per-
    executor ``Executor(observe=...)`` overrides this for its own step
    telemetry; the reader pipeline and trainer reports consult it."""
    try:
        from .. import flags
        return bool(flags.get_flag("observe"))
    except KeyError:
        return False


# Module-level write helpers — THE gated surface: tests/test_repo_lint.py
# requires the name argument at every call site to be a string literal
# registered in METRIC_NAMES.
def inc_counter(name: str, n: float = 1.0):
    _registry.inc_counter(name, n)


def set_gauge(name: str, value: float, label: str = ""):
    _registry.set_gauge(name, value, label)


def observe_hist(name: str, value: float):
    _registry.observe_hist(name, value)
