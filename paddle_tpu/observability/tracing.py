"""Structured tracing spans: the causal-timeline half of observability.

PR 5's metrics answer "how fast is it" (histograms, counters); this
module answers "**why**" — every unit of work on the reader → staging →
dispatch → fetch chain (and the serving admit → batch → dispatch → reply
chain) emits one **span** record into the existing JSONL stream::

    {"ts": ..., "kind": "span", "name": "executor/dispatch",
     "trace": "t3f2a-1", "span": "3f2a-4", "parent": "3f2a-2",
     "t0": <unix s>, "dur_ms": 12.4, "labels": {...}, "events": [...]}

* **trace** — one causal tree: a serving request's lifecycle, or one
  ``run_pipelined`` generator run with its staging and dispatch children.
* **span** / **parent** — the tree edges.  Parent linkage is implicit
  (a thread-local stack maintained by the :func:`span` context manager /
  :func:`attach`) or explicit (``parent=`` for cross-thread children:
  the staging worker's spans parent to the pipelined root; a serving
  request's span starts on the submitting thread and ends on the
  dispatcher thread).
* **events** — point-in-time annotations riding inside a span (retry
  attempts at the dispatch rims, circuit-breaker transitions), the
  causal complement of the ``fault/*`` JSONL events.

Span names are LITERAL members of the frozen :data:`SPAN_NAMES` table —
the same discipline as ``metrics.METRIC_NAMES``, with the same repo-lint
AST gate (``tests/test_repo_lint.py``): a typo'd span name is a test
failure, not a silently orphaned timeline.

**Zero overhead when off** is inherited from PR 5's contract: span
creation sites are gated by their callers (``Executor._observing()``,
the reader engine's ``instrument`` resolution), never here — with
``observe`` off the hot paths construct no Span objects, write no
metrics, emit no JSONL, and cannot retrace (tier-1 counter-delta +
``retrace_guard`` assertions).  Emission itself is a no-op when no
``metrics_log`` is set, so spans cost ~a dict build when observing
without an export sink.

``python -m paddle_tpu trace <log.jsonl>`` replays a log's spans into
per-trace timelines, critical paths and per-name latency stats;
:func:`build_traces` / :func:`span_stats` are the library form.
"""
from __future__ import annotations

import itertools
import os
import threading
import time
from typing import Dict, List, Optional, Tuple

from . import export as _export

__all__ = [
    "SPAN_NAMES", "Span", "span", "start_span", "current_span",
    "add_event", "attach", "ROOT",
    "CTX_VERSION", "RemoteParent", "inject", "extract",
    "extract_traceparent",
    "build_traces", "span_stats", "critical_path", "render_trace",
]

# ---------------------------------------------------------------------------
# Frozen span-name registry.  (name, help) — names passed to span()/
# start_span() MUST appear here as literals (AST-gated in
# tests/test_repo_lint.py; duplicates rejected at import AND lint time).
# ---------------------------------------------------------------------------
SPAN_NAMES = (
    ("executor/step", "one Executor.run / run_steps call end to end "
     "(dispatch + state writeback + fetch materialization); labels: "
     "path, steps, fingerprint"),
    ("executor/dispatch", "the compiled-step call itself, inside the "
     "fault-tolerance rim (retry attempts attach as span events)"),
    ("executor/fetch_block", "host time materializing fetches to numpy "
     "(the return_numpy conversion barrier)"),
    ("executor/run_pipelined", "root of one pipelined generator run; "
     "staging and dispatch spans are its children"),
    ("pipeline/stage", "staging worker: stack_feeds + device_put for "
     "one dispatch chunk (kind=scan) or one feed (kind=single)"),
    ("reader/pipeline", "root of one prefetch/interleave engine run "
     "(instrumented); per-item worker spans are its children"),
    ("reader/item", "one worker-produced item: the source pull (decode/"
     "feed build) up to the queue offer"),
    ("serving/request", "request lifecycle admit -> terminal completion "
     "(one trace per request; ends with status=ok or the typed error)"),
    ("serving/batch", "one coalesced serving batch: staging pickup -> "
     "dispatch -> reply; labels link member request ids and traces"),
    ("serving/decode_step", "one token step of a decode slot pool: the "
     "batched incremental-decode dispatch advancing every live slot by "
     "one token (retry attempts attach as span events); labels: model, "
     "active, step"),
    ("http/request", "one HTTP front request: socket read -> backend "
     "submit(s) -> last response byte; labels: method, path, status"),
    ("fleet/autoscale", "one executed autoscaler decision: trigger "
     "snapshot -> replica added or drained+removed; decision details "
     "attach as span events"),
    ("opprof/op", "one op's measured windows in a per-op profile run "
     "(observability.opprof eager replay); labels: op_type, index"),
    ("elastic/resize", "one committed mesh resize boundary of the "
     "elastic training service: drain -> merge replicas -> re-plan -> "
     "re-shard -> relaunch; phase completions attach as span events"),
    ("sparse/pull", "one batch's pre-dispatch sparse-table pulls "
     "(id dedup + cache-first row fetch + feed injection across all "
     "bound tables); labels: tables"),
    ("sparse/push", "one batch's post-dispatch gradient pushes (host-"
     "side sparse optimizer update across all bound tables, inside the "
     "sparse.push fault-injection/retry rim); labels: tables"),
    ("sparse/prefetch", "root of one pull-ahead prefetch run over a "
     "feed stream (SparseSession.prefetch_feeds): the worker thread's "
     "per-batch sparse/pull spans cross-thread-parent to it; labels: "
     "depth"),
    ("pserver/rpc", "one client round against the pserver fleet: "
     "partition ids by shard -> write every shard's batched frame -> "
     "read every reply (pipelined, so N-shard latency is max not sum); "
     "retry attempts attach as span events; labels: op, table, shards — "
     "and, parented onto the remote caller via the wire ctx field, one "
     "server-side frame (labels: side=server, op, shard, queue_ms, "
     "kernel_ms)"),
    ("master/rpc", "server-side handling of one master RPC, parented "
     "onto the remote caller via the envelope ctx field (only emitted "
     "when the caller propagated a context); labels: method"),
)

_REGISTERED = tuple(n for n, _ in SPAN_NAMES)
if len(set(_REGISTERED)) != len(_REGISTERED):      # pragma: no cover
    raise ValueError("duplicate span name in SPAN_NAMES")
_REGISTERED_SET = frozenset(_REGISTERED)

# Sentinel parent: force a NEW root trace even when a thread-local span
# is active (serving requests are one-trace-per-request by contract).
ROOT = object()

_ids = itertools.count(1)
_prefix = f"{os.getpid() & 0xfffff:05x}"
_tls = threading.local()


def _next_id() -> str:
    return f"{_prefix}-{next(_ids):x}"


def current_span() -> Optional["Span"]:
    """Innermost span attached to THIS thread (via :func:`span` /
    :func:`attach`), or None."""
    stack = getattr(_tls, "stack", None)
    return stack[-1] if stack else None


class Span:
    """One timed unit of work.  Construct via :func:`start_span` (or the
    :func:`span` context manager); finish exactly once with :meth:`end`
    — which emits the JSONL record — or discard with :meth:`cancel`."""

    __slots__ = ("name", "trace_id", "span_id", "parent_id", "t0",
                 "labels", "events", "_t0_perf", "_done")

    def __init__(self, name: str, trace_id: str, parent_id: Optional[str],
                 labels: Dict[str, object]):
        self.name = name
        self.trace_id = trace_id
        self.span_id = _next_id()
        self.parent_id = parent_id
        self.t0 = time.time()
        self._t0_perf = time.perf_counter()
        self.labels = labels
        self.events: List[dict] = []
        self._done = False

    def event(self, name: str, **fields):
        """Attach a point-in-time event (retry, breaker transition) to
        this span; rides inside the span's JSONL record."""
        if self._done:
            return
        self.events.append({"name": str(name),
                            "ts": round(time.time(), 6), **fields})

    def end(self, **labels):
        """Finish the span and emit its record (idempotent)."""
        if self._done:
            return
        self._done = True
        dur_ms = (time.perf_counter() - self._t0_perf) * 1e3
        if labels:
            self.labels = {**self.labels, **labels}
        payload = {"name": self.name, "trace": self.trace_id,
                   "span": self.span_id, "parent": self.parent_id,
                   "t0": round(self.t0, 6), "dur_ms": round(dur_ms, 3)}
        if self.labels:
            payload["labels"] = self.labels
        if self.events:
            payload["events"] = self.events
        _export.emit_event("span", **payload)

    def cancel(self):
        """Discard without emitting (e.g. the reader's final empty pull)."""
        self._done = True

    def __repr__(self):
        return (f"Span({self.name!r}, trace={self.trace_id}, "
                f"span={self.span_id}, parent={self.parent_id})")


def start_span(name: str, parent=None, **labels) -> Span:
    """Begin a span.  ``parent``: another :class:`Span` (cross-thread
    linkage), :data:`ROOT` (force a new trace), or None (the calling
    thread's current span, else a new trace).  Labels must be
    JSON-serializable."""
    if name not in _REGISTERED_SET:
        raise KeyError(
            f"unknown span name {name!r}; span names are frozen in "
            f"observability.tracing.SPAN_NAMES — add it there (the repo "
            f"lint enforces literal, registered names)")
    if parent is None:
        parent = current_span()
    elif parent is ROOT:
        parent = None
    if parent is None:
        trace_id, parent_id = "t" + _next_id(), None
    else:
        trace_id, parent_id = parent.trace_id, parent.span_id
    return Span(name, trace_id, parent_id, labels)


class _SpanContext:
    """``with span(...)``: pushes onto the thread-local stack, ends the
    span on exit.  Also usable around a yield-free region only —
    generators should hold a Span and use :func:`attach` per resume."""

    __slots__ = ("_sp",)

    def __init__(self, sp: Span):
        self._sp = sp

    def __enter__(self) -> Span:
        _tls.__dict__.setdefault("stack", []).append(self._sp)
        return self._sp

    def __exit__(self, *exc):
        stack = getattr(_tls, "stack", None)
        if stack and stack[-1] is self._sp:
            stack.pop()
        self._sp.end()
        return False


def span(name: str, parent=None, **labels) -> _SpanContext:
    """Context manager: start a span, make it the thread's current span,
    end it on exit."""
    return _SpanContext(start_span(name, parent=parent, **labels))


class _AttachContext:
    __slots__ = ("_sp",)

    def __init__(self, sp: Span):
        self._sp = sp

    def __enter__(self) -> Span:
        _tls.__dict__.setdefault("stack", []).append(self._sp)
        return self._sp

    def __exit__(self, *exc):
        stack = getattr(_tls, "stack", None)
        if stack and stack[-1] is self._sp:
            stack.pop()
        return False


def attach(sp: Span) -> _AttachContext:
    """Make ``sp`` the thread's current span for a region WITHOUT ending
    it on exit — how a long-lived root (a pipelined generator) parents
    the spans created inside each resume."""
    return _AttachContext(sp)


def add_event(name: str, **fields):
    """Attach an event to the calling thread's current span (no-op when
    none is active)."""
    sp = current_span()
    if sp is not None:
        sp.event(name, **fields)


# ---------------------------------------------------------------------------
# Cross-process context propagation (the Dapper-style wire rim)
# ---------------------------------------------------------------------------
# Compact versioned encoding "1:<trace>:<span>".  ":" because span ids
# already contain "-" (pid-prefix-counter); a future format bump changes
# the leading version and old receivers reject-and-count, never crash.
CTX_VERSION = 1


class RemoteParent:
    """Parent carrier extracted from a wire context: just the two ids a
    child span needs.  Duck-types the ``parent=`` argument of
    :func:`start_span` (which reads only ``trace_id``/``span_id``), so a
    server-side span parents onto its remote caller exactly like a
    cross-thread one."""

    __slots__ = ("trace_id", "span_id")

    def __init__(self, trace_id: str, span_id: str):
        self.trace_id = trace_id
        self.span_id = span_id

    def __repr__(self):
        return (f"RemoteParent(trace={self.trace_id}, "
                f"span={self.span_id})")


def inject(sp: Optional[Span] = None) -> Optional[str]:
    """Wire encoding of ``sp`` (default: the calling thread's current
    span); None when there is nothing to propagate — callers add no wire
    field in that case, keeping frames byte-identical when not observing."""
    if sp is None:
        sp = current_span()
    if sp is None:
        return None
    return f"{CTX_VERSION}:{sp.trace_id}:{sp.span_id}"


def _reject_ctx():
    from . import metrics as _metrics
    _metrics.inc_counter("trace/context_rejected")
    return None


def extract(ctx) -> Optional[RemoteParent]:
    """Decode a wire context produced by :func:`inject`.  An ABSENT
    context (None) is normal and returns None silently; a PRESENT but
    malformed/unknown-version one is ignored-and-counted
    (``trace/context_rejected``) — propagation failures degrade to a
    fresh trace, never to a failed request."""
    if ctx is None:
        return None
    if not isinstance(ctx, str):
        return _reject_ctx()
    parts = ctx.split(":")
    if len(parts) != 3 or parts[0] != str(CTX_VERSION) \
            or not parts[1] or not parts[2]:
        return _reject_ctx()
    return RemoteParent(parts[1], parts[2])


def extract_traceparent(header) -> Optional[RemoteParent]:
    """Decode a W3C ``traceparent`` request header
    (``<2 hex version>-<32 hex trace-id>-<16 hex parent-id>-<2 hex
    flags>``) into a parent carrier.  The foreign ids are adopted
    verbatim (trace id prefixed ``t`` like locally-minted ones), so an
    edge client's trace id groups our server-side spans with its own.
    Same reject contract as :func:`extract`: absent -> None silently,
    malformed/all-zero/unsupported-version -> ignored-and-counted."""
    if header is None:
        return None
    if not isinstance(header, str):
        return _reject_ctx()
    parts = header.strip().split("-")
    if len(parts) < 4:
        return _reject_ctx()
    version, trace, parent = parts[0], parts[1], parts[2]
    hexdigits = "0123456789abcdef"
    if (len(version) != 2 or len(trace) != 32 or len(parent) != 16
            or any(c not in hexdigits for c in version + trace + parent)
            or version == "ff"
            or trace == "0" * 32 or parent == "0" * 16):
        return _reject_ctx()
    return RemoteParent("t" + trace, parent)


# ---------------------------------------------------------------------------
# Trace reconstruction (the `python -m paddle_tpu trace` engine)
# ---------------------------------------------------------------------------
def build_traces(events) -> List[dict]:
    """Group a log's span events into traces, time-ordered.

    Returns ``[{"trace": id, "t0": s, "dur_ms": span-of-spans wall,
    "spans": [span events sorted by t0], "roots": [...]}, ...]`` sorted
    by first span start.  Span events missing ids are skipped.
    """
    by_trace: Dict[str, List[dict]] = {}
    for e in events:
        if e.get("kind") != "span":
            continue
        tid = e.get("trace")
        if not tid or not e.get("span"):
            continue
        by_trace.setdefault(tid, []).append(e)
    traces = []
    for tid, spans in by_trace.items():
        spans.sort(key=lambda e: (e.get("t0", 0.0), e.get("span", "")))
        ids = {e["span"] for e in spans}
        roots = [e for e in spans
                 if not e.get("parent") or e["parent"] not in ids]
        t0 = min(e.get("t0", 0.0) for e in spans)
        t1 = max(e.get("t0", 0.0) + e.get("dur_ms", 0.0) / 1e3
                 for e in spans)
        traces.append({"trace": tid, "t0": t0,
                       "dur_ms": round((t1 - t0) * 1e3, 3),
                       "spans": spans, "roots": roots})
    traces.sort(key=lambda t: t["t0"])
    return traces


def span_stats(events) -> Dict[str, dict]:
    """Per-span-name latency stats over a log: count, total, p50/p99/max
    of dur_ms."""
    durs: Dict[str, List[float]] = {}
    for e in events:
        if e.get("kind") == "span" and e.get("name"):
            durs.setdefault(e["name"], []).append(float(e.get("dur_ms", 0.0)))
    out = {}
    for name, ds in sorted(durs.items()):
        ds.sort()
        n = len(ds)
        out[name] = {
            "count": n, "total_ms": round(sum(ds), 3),
            "p50_ms": round(ds[n // 2], 3),
            "p99_ms": round(ds[min(n - 1, int(n * 0.99))], 3),
            "max_ms": round(ds[-1], 3),
        }
    return out


def critical_path(trace: dict) -> List[dict]:
    """Longest root→leaf chain by end time: from the latest-ending root,
    repeatedly descend into the child whose end time is latest.  The
    chain names where a trace's wall clock actually went."""
    spans = trace["spans"]
    children: Dict[str, List[dict]] = {}
    for e in spans:
        if e.get("parent"):
            children.setdefault(e["parent"], []).append(e)

    def end(e):
        return e.get("t0", 0.0) + e.get("dur_ms", 0.0) / 1e3

    path = []
    roots = trace["roots"] or spans[:1]
    node = max(roots, key=end, default=None)
    seen = set()
    while node is not None and node["span"] not in seen:
        seen.add(node["span"])
        path.append(node)
        kids = children.get(node["span"], [])
        node = max(kids, key=end, default=None)
    return path


def render_trace(trace: dict, max_spans: int = 40) -> str:
    """Indented timeline of one trace: offset from trace start, name,
    duration, labels; children nest under parents."""
    spans = trace["spans"]
    by_id = {e["span"]: e for e in spans}
    depth: Dict[str, int] = {}

    def d(e):
        sid = e["span"]
        if sid in depth:
            return depth[sid]
        p = e.get("parent")
        depth[sid] = 0 if not p or p not in by_id else d(by_id[p]) + 1
        return depth[sid]

    lines = [f"trace {trace['trace']}  ({len(spans)} span(s), "
             f"{trace['dur_ms']} ms)"]
    for e in spans[:max_spans]:
        off = (e.get("t0", 0.0) - trace["t0"]) * 1e3
        labels = e.get("labels") or {}
        lbl = " ".join(f"{k}={v}" for k, v in sorted(labels.items())
                       if not isinstance(v, (list, dict)))
        evs = "".join(f" !{ev['name']}" for ev in e.get("events", []))
        lines.append(f"  {'  ' * d(e)}[+{off:9.2f} ms] {e['name']} "
                     f"({e.get('dur_ms', 0.0):.2f} ms)"
                     + (f"  {lbl}" if lbl else "") + evs)
    if len(spans) > max_spans:
        lines.append(f"  ... {len(spans) - max_spans} more span(s)")
    return "\n".join(lines)
