"""NaN/Inf provenance: eagerly bisect a failing step to the op that
first produced a non-finite value.

``Executor(check_nan_inf=True)`` detects non-finites with in-graph finite
flags (core/executor.py ``_nan_localize`` — executor.cc:116-124 analog),
which names a producer by PROGRAM order.  This module goes one step
further on the failure path: it re-runs the exact failing step EAGERLY —
same feeds, same pre-step state, same step-counter-derived PRNG key — one
``run_op`` at a time, checking every produced value on the host, so the
diagnostic carries the first non-finite producer in EXECUTION order with
shapes and NaN/Inf element counts.  For programs with a ``backward`` op
the forward slice is walked eagerly first (forward producers bisect
exactly); if the forward stays finite, the gradient pass runs as a whole
and each ``<p>@GRAD`` is checked by name.

One-shot and failure-path only: the bisect costs an extra eager step, paid
exactly once, after a step already failed.
"""
from __future__ import annotations

import logging
from typing import Dict, Optional

logger = logging.getLogger("paddle_tpu")

__all__ = ["bisect_step", "format_diagnosis", "make_eager_context"]


def make_eager_context(executor, program, feed_arrays, state, step: int,
                       is_test: bool = False):
    """``(env, ctx, bw_idx)`` for an eager per-op replay of one step,
    replicating the compiled step's input dtype coercion EXACTLY
    (core/executor.py ``_make_fn``): compute_dtype upcast first, then
    pure-inference AMP bf16.  Shared by the NaN bisect here and the
    per-op profiler (``observability.opprof``) so both replay at the
    SAME precision the compiled step computed at — a diagnosis or a
    per-op timing taken at another precision would describe a different
    computation."""
    import jax
    import jax.numpy as jnp

    from ..core.executor import Env, LoweringContext, _to_bf16

    ops = program.global_block().ops
    bw_idx = next((i for i, op in enumerate(ops)
                   if op.type == "backward"), None)

    env = Env(program.global_block())
    env.local.update({k: jnp.asarray(v) for k, v in state.items()})
    env.local.update({k: jnp.asarray(v) for k, v in feed_arrays.items()})
    if executor.compute_dtype is not None:
        cd = jnp.dtype(executor.compute_dtype)
        env.local = {k: v.astype(cd) if hasattr(v, "dtype")
                     and jnp.issubdtype(v.dtype, jnp.floating)
                     else v for k, v in env.local.items()}
    if executor.amp and bw_idx is None:
        env.local = {k: _to_bf16(v) for k, v in env.local.items()}

    base_key = jax.random.fold_in(
        jax.random.PRNGKey(program.random_seed), step)
    ctx = LoweringContext(
        program, base_key, is_test=is_test, amp=executor.amp,
        mesh=getattr(executor, "mesh", None),
        compute_dtype=executor.compute_dtype,
        conv1x1_pallas=executor.conv1x1_pallas)
    return env, ctx, bw_idx


def _nonfinite(value) -> Optional[Dict[str, int]]:
    """{'nan': n, 'inf': n} when ``value`` holds non-finite floats."""
    import jax.numpy as jnp
    import numpy as np
    if not (hasattr(value, "dtype")
            and jnp.issubdtype(value.dtype, jnp.floating)):
        return None
    a = np.asarray(value)
    if np.all(np.isfinite(a)):
        return None
    return {"nan": int(np.isnan(a).sum()), "inf": int(np.isinf(a).sum())}


def _check_outputs(op, op_index, env, phase) -> Optional[dict]:
    for slot, names in op.outputs.items():
        for name in names:
            if not env.has(name):
                continue
            bad = _nonfinite(env.get(name))
            if bad is not None:
                value = env.get(name)
                return {
                    "op_index": op_index, "op_type": op.type, "var": name,
                    "slot": slot, "phase": phase,
                    "shape": list(getattr(value, "shape", ())),
                    "dtype": str(getattr(value, "dtype", "?")),
                    "nan_count": bad["nan"], "inf_count": bad["inf"],
                }
    return None


def bisect_step(executor, program, feed_arrays, state, step: int,
                is_test: bool = False) -> Optional[dict]:
    """Eagerly re-run one step and return a provenance dict for the first
    non-finite producer, or None when the re-run stays finite (or the
    bisect itself fails — it must never mask the original error).

    ``state`` must be the PRE-step values — check_nan_inf step variants
    compile without buffer donation (core/compile_cache.CachedStep
    ``donate=False``) exactly so these stay valid on the failure path.
    """
    try:
        return _bisect(executor, program, feed_arrays, state, step, is_test)
    except Exception as e:
        logger.warning("NaN-provenance bisect failed (%s: %s); reporting "
                       "the in-graph localization only",
                       type(e).__name__, e)
        return None


def _bisect(executor, program, feed_arrays, state, step, is_test):
    from ..core.executor import _run_backward, grad_var_name, run_op

    ops = program.global_block().ops
    # the shared context replicates the compiled step's input dtype
    # coercion — a non-finite that arose at the compiled precision must
    # reproduce at the SAME precision, or the bisect could blame the
    # wrong op
    env, ctx, bw_idx = make_eager_context(
        executor, program, feed_arrays, state, step, is_test)

    # a poisoned INPUT is not an op's fault — report it as the feed/state
    # (checked AFTER the casts: what the compiled step actually consumed)
    for name, value in env.local.items():
        bad = _nonfinite(value)
        if bad is not None:
            return {"op_index": -1, "op_type": None, "var": name,
                    "slot": None,
                    "phase": "feed" if name in feed_arrays else "state",
                    "shape": list(getattr(value, "shape", ())),
                    "dtype": str(getattr(value, "dtype", "?")),
                    "nan_count": bad["nan"], "inf_count": bad["inf"]}

    for idx, op in enumerate(ops):
        if idx == bw_idx:
            # the forward slice (indices < bw_idx) already ran eagerly,
            # per-op checked, in earlier iterations — here only the
            # gradient pass remains; it runs whole (grads come from ONE
            # value_and_grad) and each produced @GRAD is checked by name
            _run_backward(ops[:bw_idx], op, env, ctx)
            for pname in op.attrs.get("params", ()):
                gname = grad_var_name(pname)
                if not env.has(gname):
                    continue
                bad = _nonfinite(env.get(gname))
                if bad is not None:
                    g = env.get(gname)
                    return {"op_index": idx, "op_type": "backward",
                            "var": gname, "slot": None, "phase": "backward",
                            "shape": list(getattr(g, "shape", ())),
                            "dtype": str(getattr(g, "dtype", "?")),
                            "nan_count": bad["nan"],
                            "inf_count": bad["inf"]}
            continue
        run_op(op, env, ctx)
        phase = "forward" if bw_idx is None or idx < bw_idx else "update"
        found = _check_outputs(op, idx, env, phase)
        if found is not None:
            return found
    return None


def format_diagnosis(diag: dict) -> str:
    """One-line human rendering of a provenance dict."""
    where = (f"op #{diag['op_index']} {diag['op_type']!r}"
             if diag.get("op_type") else diag["phase"])
    return (f"first non-finite value produced by {where} -> var "
            f"{diag['var']!r} (phase {diag['phase']}, shape "
            f"{diag['shape']}, dtype {diag['dtype']}, "
            f"{diag['nan_count']} NaN / {diag['inf_count']} Inf elements)")
